"""Pipeline parallelism: GPipe stages over the "pp" mesh axis.

Capability beyond the reference (SURVEY.md section 2.3 lists PP as absent).
TPU-first formulation: the model's blocks are ALREADY a stacked (L, ...)
parameter tree (the lax.scan layout) — pipeline parallelism is nothing more
than sharding that leading layer axis over a mesh axis
(`PartitionSpec("pp", ...)`, vitax/parallel/sharding.py:param_pspec) and
running the stage schedule inside `jax.shard_map`:

- Stage s holds layers [s*L/S, (s+1)*L/S) — its shard of the stacked tree.
- The local batch is split into M microbatches (`--pp_microbatches`,
  default S). At tick t (t = 0..M+S-2), stage s processes microbatch t-s
  (bubble ticks compute masked garbage — lockstep SPMD, standard GPipe),
  then hands its activation to stage s+1 via `jax.lax.ppermute` — one ICI
  hop, overlapped with the next tick's compute by XLA's scheduler.
- The last stage's valid outputs are the tick outputs [S-1, S-1+M); a psum
  over "pp" (one nonzero contributor) replicates them so the head/loss run
  under plain GSPMD afterwards.
- Topology placement: "pp" is the second-to-last mesh axis ("ep" is last
  and batch-like), so pp neighbors are mesh-ADJACENT device ids — on pods
  the per-tick stage hop always rides the closest ICI links and never the
  host boundary; the dp/fsdp axes (larger strides) carry the cross-host
  traffic, which is amortized once per step (grad reduction), not once per
  tick. tests/test_multiprocess.py exercises exactly that composition.
- Backward is plain autodiff through the scan/ppermute: bubble-tick
  computations receive zero cotangents (their outputs are masked), so only
  real microbatches contribute gradients, which land on each stage's own
  param shard.

Composes with dp, fsdp/ZeRO-3, AND tp/sp: block params may carry "fsdp"
placements on their weight dims in addition to "pp" on the layer dim, and
"tp" placements on their Megatron dims.
- sp rides as another MANUAL axis of the pipeline shard_map: activations
  keep their token dim sharded over "sp" through the whole schedule, and
  the ring/ulysses LOCAL bodies run directly inside the already-manual
  region (vitax_pp_impl — no nested shard_map: in jax 0.9 a nested
  partial-manual map hoists its closure constants into sdy wrappers whose
  all-axes sharding encodings violate Shardy's manual-before-free ordering).
- tp stays a GSPMD-AUTO axis: the shard_map manualizes every mesh axis
  except "tp" (with vma tracking on, so autodiff residual specs are
  inferred precisely), and the compiler partitions the block matmuls from
  the weights' own Megatron placements exactly as on the scan path.
  Attention under tp uses the dense einsum path (GSPMD shards it over the
  tp-global head dim; a Pallas kernel cannot be auto-partitioned — at ViT
  sequence lengths the dense path measured ~1.9% of step time at 10B
  dims on v5e — BASELINE.md round-5 attention A/B).
Inside the pipeline body each block's leaves are all-gathered
over "fsdp" right before use — the manual form of the per-block gather
GSPMD emits on the scan path — and autodiff's transpose of that gather is
a reduce-scatter, so gradients land back on the ZeRO-3 shards. With remat
the gather sits inside the checkpointed block, so the backward re-gathers
instead of keeping gathered weights live: full ZeRO-3 memory semantics
inside GPipe. Embed/head run data-parallel outside the pipeline, reusing
the SAME param tree as the scan path functionally — init and checkpoints
are identical between pp and non-pp topologies, so Orbax cross-topology
restore covers pp<->fsdp resizes.

v2 additions over the original GPipe body:
- Dropout rides the pipeline: per-(tick, layer, data-shard) keys are folded
  from the step rng inside the body, so masks are deterministic given
  (seed, step) and distinct across microbatches, layers, and batch shards.
  Position dropout applies outside the shard_map (plain GSPMD).
- MoE blocks work under pp: each block's sown load-balance ingredients
  (frac_tokens, mean_prob — LINEAR in the tokens) are masked on bubble
  ticks, averaged over microbatches and data shards, and only then combined
  into the nonlinear Switch aux product — so the pipeline's aux equals the
  scan path's exactly.

v3 (round 5): expert parallelism composes too (--ep_size > 1 with
--pp_size > 1): "ep" is already a manual axis of the pipeline shard_map, so
the MoeMlp runs its own tiled all_to_all pair over it and declares expert
params at the local (E/ep, ...) shard shape (vitax/models/moe.py MoeMlp
.ep_axis/.ep_size) — the hand-written form of the batch<->expert exchange
GSPMD derives from dispatch_sharding on the scan path. einsum impl only
(config.validate).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from vitax.config import Config
from vitax.parallel.mesh import BATCH_AXES, optimization_barrier, shard_map
from vitax.platform import backend_platform


def _gather_over(x, spec: P, axis_name: str):
    """All-gather the dims of `x` that `spec` places on `axis_name` (tiled:
    the gathered dim returns to its full size in place)."""
    for dim, ax in enumerate(spec):
        if ax == axis_name:
            x = jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)
    return x


def _drop_tp(spec: P) -> P:
    """Strip "tp" placements from a PartitionSpec: when tp is a GSPMD-auto
    axis, partial-manual shard_map in_specs may only name manual axes; the
    tp sharding rides on the arrays' own NamedShardings."""
    def fix(entry):
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a != "tp")
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return None if entry == "tp" else entry
    return P(*(fix(e) for e in spec))


def make_pp_forward(cfg: Config, model, mesh: Mesh, block_specs=None):
    """(params, images, det=True, rng=None, with_aux=False) -> logits or
    (logits, moe_aux), GPipe-pipelined over "pp".

    `model` is the same VisionTransformer the scan path uses — its param tree
    is reused leaf-for-leaf; this function only changes HOW blocks are
    applied. `block_specs` is the PartitionSpec tree of the stacked block
    params (P("pp", ...) with optional "fsdp" dims) — when omitted, a
    pp-only layout is assumed (stage params whole per device).
    """
    import flax.linen as nn

    from vitax.models.vit import _REMAT_POLICIES, Block

    S = mesh.shape["pp"]
    M = cfg.pp_microbatches or S
    assert cfg.num_blocks % S == 0, (cfg.num_blocks, S)
    Lps = cfg.num_blocks // S  # layers per stage
    dp_like = (mesh.shape["dp"] * mesh.shape["fsdp"] * mesh.shape["ep"])
    assert cfg.batch_size % (dp_like * M) == 0, (
        f"batch {cfg.batch_size} must divide by data-axes*microbatches "
        f"({dp_like}*{M})")
    moe = cfg.moe_experts > 0
    # tp present: partial-manual shard_map (tp stays GSPMD-auto) with vma
    # tracking (see the shard_map call below); absent: full-manual,
    # round-3 behavior. sp is ALWAYS manual: the ring/ulysses bodies run
    # directly in the pipeline body over the in-scope "sp" axis.
    tp_auto = mesh.shape["tp"] > 1
    if (tp_auto and cfg.dtype == "bfloat16"
            and backend_platform() == "cpu"):
        # a warning here would be followed by a native XLA abort the user
        # can't connect back to it (ADVICE r4) — fail loudly instead
        raise ValueError(
            "pp x tp with bf16 on the CPU backend crashes XLA's "
            "operand_upcaster pass (CPU bf16-dot emulation mishandles "
            "partitioner-generated copies in the pipeline's scan loops). "
            "This pass does not exist in TPU's native-bf16 compile "
            "pipeline. Use --dtype float32 for CPU runs of this mesh.")
    sp = mesh.shape["sp"]
    if sp > 1:
        assert cfg.num_patches % sp == 0, (
            f"pp x sp needs num_patches {cfg.num_patches} divisible by "
            f"sp {sp}")
    has_block_dropout = cfg.att_dropout > 0 or cfg.mlp_dropout > 0

    # the model's attention impl may be shard_map-wrapped (multi-device
    # meshes); inside pipeline_body the batch/pp/sp axes are ALREADY manual,
    # so swap to the pp-body variant: the raw local kernel when tp/sp are
    # absent, the LOCAL ring/ulysses body under sp (the "sp" axis is in
    # scope), or None under tp (dense einsum path — GSPMD partitions it
    # over the tp-auto head dim). Same selection, incl. the dryrun's
    # interpret-mode forcing.
    bk = model.block_kwargs()
    _impl = bk["attention_impl"]
    bk["attention_impl"] = getattr(
        _impl, "vitax_pp_impl", getattr(_impl, "vitax_local_impl", _impl))
    if sp > 1:
        # under manual sp the Block's dense fallback would softmax each
        # LOCAL N/sp token shard as if it were the full sequence —
        # shape-correct, silently wrong. The body impl must be sp-aware
        # (ring/ulysses local); it is None when make_attention_impl bailed
        # (e.g. num_heads % tp != 0) or the model was built without one.
        assert bk["attention_impl"] is not None, (
            "pp x sp needs an sp-aware attention impl in the pipeline body "
            "(ring/ulysses via make_attention_impl); got None — check "
            "num_heads divisibility by tp (and sp*tp for ulysses)")
        # att_dropout under manual sp must ride an sp-aware DROPOUT body
        # (both ring and ulysses carry one at tp=1, round 5); the dense
        # fallback would softmax local token shards — silently wrong
        assert cfg.att_dropout == 0.0 or getattr(
            bk["attention_impl"], "vitax_dropout", None) is not None, (
            "pp x sp with --att_dropout > 0 needs a body impl with an "
            "in-kernel dropout variant (ring/ulysses carry one at tp=1; "
            "under tp the body impl has none)")
    # mesh-level sharding anchors are meaningless on the per-device values
    # inside shard_map (and NamedSharding constraints are illegal there)
    bk["token_sharding"] = None
    bk["moe_dispatch_sharding"] = None
    if moe and mesh.shape["ep"] > 1:
        # expert parallelism inside the manual body: the MoeMlp runs its own
        # tiled all_to_all pair over the in-scope "ep" axis and declares its
        # expert params at the local (E/ep, ...) shard shape — the manual
        # form of the a2a GSPMD derives from dispatch_sharding on the scan
        # path (vitax/models/moe.py)
        bk["moe_ep_axis"] = "ep"
        bk["moe_ep_size"] = mesh.shape["ep"]
    block = Block(**bk)

    # manual-axis view of the block specs: tp placements are stripped when
    # tp is GSPMD-auto (the arrays' own NamedShardings carry them), then
    # per-layer specs drop the leading (stacked/"pp") dim of each leaf spec
    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    manual_block_specs = (None if block_specs is None else
                          (jax.tree.map(_drop_tp, block_specs,
                                        is_leaf=is_spec)
                           if tp_auto else block_specs))
    layer_specs = (None if manual_block_specs is None else jax.tree.map(
        lambda s: P(*s[1:]), manual_block_specs, is_leaf=is_spec))

    def make_one_block(det: bool, collect_aux: bool):
        def one_block(carry, xs):
            layer_params, key = xs
            if layer_specs is not None and mesh.shape["fsdp"] > 1:
                # pin the gathers to the loop iteration: the sharded layer
                # slice alone is loop-invariant enough for XLA's LICM to
                # hoist the per-block all-gathers out of the layer scan,
                # materializing the whole STAGE's gathered parameters at
                # once (28.7 GB vs 10.1 GB temps at the 10B flagship shape —
                # caught by test_10b_shape_lowers_under_pipeline_fsdp). The
                # barrier makes the gather input depend on the loop carry.
                layer_params, carry = optimization_barrier(
                    (layer_params, carry))
                # ZeRO-3 inside the pipeline: gather this block's shards over
                # "fsdp" just-in-time (under remat this sits inside the
                # checkpointed region, so backward re-gathers rather than
                # holding gathered weights live; the gather's transpose
                # reduce-scatters the weight cotangents onto the shards).
                # NOTE specs lead the tree.map: P is a tuple subclass, so it
                # must be the is_leaf-guarded first tree
                layer_params = jax.tree.map(
                    lambda s, x: _gather_over(x, s, "fsdp"),
                    layer_specs, layer_params, is_leaf=is_spec)
            rngs = ({"dropout": key}
                    if (not det) and has_block_dropout else None)
            if collect_aux:
                y, cols = block.apply({"params": layer_params}, carry, det,
                                      rngs=rngs, mutable=["intermediates"])
                moe_cols = cols["intermediates"]["moe"]
                # sow stores a tuple of sown values (one per call)
                aux = (moe_cols["moe_frac_tokens"][0],
                       moe_cols["moe_mean_prob"][0])
            else:
                y = block.apply({"params": layer_params}, carry, det,
                                rngs=rngs)
                aux = None
            return y, aux
        if cfg.grad_ckpt:
            one_block = jax.checkpoint(
                one_block, policy=_REMAT_POLICIES[cfg.remat_policy],
                prevent_cse=False)
        return one_block

    def make_pipeline_body(det: bool, collect_aux: bool):
        one_block = make_one_block(det, collect_aux)

        def stage_fn(stage_params, x, tick_key):
            # per-layer dropout keys: the tick key folded with the GLOBAL
            # layer index (stage offset + local index), so every (microbatch,
            # layer) pair draws an independent mask stream
            s = jax.lax.axis_index("pp")
            layer_keys = jax.vmap(
                lambda i: jax.random.fold_in(tick_key, s * Lps + i)
            )(jnp.arange(Lps))
            y, aux = jax.lax.scan(one_block, x, (stage_params, layer_keys),
                                  unroll=min(cfg.scan_unroll, Lps))
            return y, aux  # aux: (frac (Lps, E), prob (Lps, E)) or None

        def pipeline_body(stage_params, key_data, x):
            # per-device view: stage_params = this stage's (Lps, ...) tree,
            # x = this dp-shard's (B_loc, N, D) activations (replicated over
            # pp), key_data = the step rng's raw key data (replicated)
            s = jax.lax.axis_index("pp")
            # distinct dropout streams per data shard (dp x fsdp x ep)
            shard_idx = (
                (jax.lax.axis_index("dp") * mesh.shape["fsdp"]
                 + jax.lax.axis_index("fsdp")) * mesh.shape["ep"]
                + jax.lax.axis_index("ep"))
            # sp shards hold DIFFERENT tokens of the same samples — their
            # mlp-dropout masks (drawn inside the body) must be independent
            # too (identity when sp == 1: idx*1 + 0). Pos dropout runs
            # OUTSIDE the pipeline shard_map (plain GSPMD in forward()),
            # so it is not affected by this fold.
            shard_idx = (shard_idx * mesh.shape["sp"]
                         + jax.lax.axis_index("sp"))
            base_key = jax.random.fold_in(
                jax.random.wrap_key_data(key_data), shard_idx)
            b_loc = x.shape[0]
            mbs = x.reshape(M, b_loc // M, *x.shape[1:])
            perm = [(i, (i + 1) % S) for i in range(S)]

            def tick(carry, t):
                buf, acc_f, acc_p = carry
                inj = jax.lax.dynamic_index_in_dim(
                    mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                x_in = jnp.where(s == 0, inj, buf)
                y, aux = stage_fn(stage_params, x_in,
                                  jax.random.fold_in(base_key, t))
                if collect_aux:
                    # bubble ticks (t-s outside [0, M)) computed garbage:
                    # their aux ingredients must not pollute the batch means
                    valid = jnp.logical_and(t >= s, t - s < M)
                    acc_f = acc_f + jnp.where(valid, aux[0], 0.0)
                    acc_p = acc_p + jnp.where(valid, aux[1], 0.0)
                y_out = jnp.where(s == S - 1, y, jnp.zeros_like(y))
                if S > 1:
                    # the final tick's carry is never read — skip its ICI hop
                    # (cond predicate is uniform across devices, so the
                    # collective stays SPMD-legal; cf. ring attention's
                    # "exactly sp-1 rotations")
                    buf = jax.lax.cond(
                        t < M + S - 2,
                        lambda v: jax.lax.ppermute(v, "pp", perm),
                        lambda v: v, y)
                else:
                    buf = y
                return (buf, acc_f, acc_p), y_out

            acc0 = (jnp.zeros((Lps, cfg.moe_experts), jnp.float32),) * 2 \
                if collect_aux else (jnp.float32(0.0),) * 2
            buf0 = jnp.zeros_like(mbs[0])
            if tp_auto and hasattr(jax.lax, "pcast"):
                # under vma tracking (the partial-manual tp path) the
                # carry's type must declare it varies over pp — the tick
                # output does (each stage holds a different activation).
                # jax 0.4.x has no vma tracking (check_rep=False on the
                # partial-auto path), so there is nothing to cast there.
                buf0 = jax.lax.pcast(buf0, ("pp",), to="varying")
            (_, acc_f, acc_p), ys = jax.lax.scan(
                tick, (buf0, *acc0),
                jnp.arange(M + S - 1))
            outs = ys[S - 1:S - 1 + M]          # microbatch i at tick S-1+i
            outs = jax.lax.psum(outs, "pp")     # one nonzero contributor
            outs = outs.reshape(b_loc, *x.shape[1:])
            if not collect_aux:
                return outs, jnp.float32(0.0)
            # per-layer means over microbatches (equal sizes) and data
            # shards: frac/prob are linear in the tokens, so these means
            # equal the scan path's full-batch means exactly
            frac = jax.lax.pmean(acc_f / M, ("dp", "fsdp", "ep"))
            prob = jax.lax.pmean(acc_p / M, ("dp", "fsdp", "ep"))
            # nonlinear Switch product only AFTER the means; sum this
            # stage's layers, then all stages' (each stage contributes its
            # own Lps rows exactly once)
            aux = cfg.moe_experts * jnp.sum(frac * prob)
            aux = jax.lax.psum(aux, "pp") / cfg.num_blocks
            return outs, aux

        return pipeline_body

    # tokens ride the manual "sp" axis when sequence parallelism is active
    act_spec = P(BATCH_AXES, "sp" if sp > 1 else None, None)

    def stacked_specs(tree):
        return jax.tree.map(
            lambda leaf: P(*("pp",) + (None,) * (leaf.ndim - 1)), tree)

    dtype = model.dtype

    def forward(params, images, det: bool = True, rng=None,
                with_aux: bool = False):
        from vitax.models.vit import apply_embed, apply_tail
        p = params["params"]
        x = apply_embed(p, images, patch_size=cfg.patch_size,
                        embed_dim=cfg.embed_dim, dtype=dtype)
        any_dropout = max(cfg.pos_dropout, cfg.att_dropout,
                          cfg.mlp_dropout) > 0
        if not det and any_dropout:
            # match the scan path's failure mode: flax raises on a missing
            # "dropout" rng rather than silently training deterministically
            assert rng is not None, (
                "non-deterministic pp forward with dropout configured "
                "needs an rng")
        use_dropout = (not det) and any_dropout
        if use_dropout and cfg.pos_dropout > 0:
            # position dropout runs OUTSIDE the shard_map (plain GSPMD);
            # the module keeps pos-dropout semantics identical to the
            # scan path's nn.Dropout site (vit.py)
            x = nn.Dropout(rate=cfg.pos_dropout).apply(
                {}, x, deterministic=False,
                rngs={"dropout": jax.random.fold_in(rng, 0x706F5D)})

        if rng is None:  # the body's key input must always be an array
            rng = jax.random.key(0)
        pipeline_body = make_pipeline_body(not use_dropout, with_aux)

        stacked = p["blocks"]
        in_specs = (manual_block_specs if manual_block_specs is not None
                    else stacked_specs(stacked))
        # tp absent: manualize every axis with vma checking off — the
        # autodiff residuals' conservative all-axes out_specs are legal
        # there (round-3 behavior, bit-identical). tp present: manualize
        # everything BUT tp and turn vma tracking ON — the residual
        # out_specs must then be inferred precisely, since naming an auto
        # axis in out_specs is an error.
        run = shard_map(
            pipeline_body, mesh=mesh,
            in_specs=(in_specs, P(), act_spec),
            out_specs=(act_spec, P()),
            axis_names=(frozenset(mesh.axis_names) - {"tp"} if tp_auto
                        else frozenset(mesh.axis_names)),
            check_vma=tp_auto)
        x, aux = run(stacked, jax.random.key_data(rng), x)

        logits = apply_tail(p, x, num_classes=cfg.num_classes, dtype=dtype)
        return (logits, aux) if with_aux else logits

    return forward
