from vitax.models.vit import (  # noqa: F401
    Attention,
    Block,
    Mlp,
    PatchEmbed,
    VisionTransformer,
    build_model,
    count_params,
)
