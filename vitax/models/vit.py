"""Vision Transformer in Flax, designed TPU-first.

Capability parity with the reference model stack (reference run_vit_training.py:99-162
composing timm 0.4.12 PatchEmbed/Block), re-designed for XLA:

- Blocks run under ``jax.lax.scan`` over stacked layer parameters (`nn.scan`):
  one traced/compiled block body regardless of depth, vs the reference's 32
  individually-wrapped modules (compile time + HLO size win).
- Activation checkpointing is `jax.remat` composed *inside* the scan, matching the
  reference's checkpoint_module-inside-FSDP order (reference run_vit_training.py:143-145).
- Computation in bfloat16 (MXU-native), parameters in float32.
- The attention inner product is pluggable: a Pallas flash-attention kernel on TPU
  (vitax.ops.attention) or the dense jnp reference path.

Architecture parity notes (verified against the reference by param-count closed form,
10,077,917,160 at default flags — see tests/test_model.py):
- conv patchify (patch_size stride/kernel) -> (B, N, D)           [timm PatchEmbed]
- learned pos_embed, shape (1, N, D), trunc-normal std 0.02; NO CLS token
  (reference run_vit_training.py:127-128)
- pre-norm blocks: LN -> MHA (fused qkv, qkv_bias=True) -> residual;
  LN -> MLP(GELU, hidden=dim*mlp_ratio) -> residual                [timm Block]
- block LayerNorm eps = 1e-5 (timm Block default when constructed directly,
  as the reference does at run_vit_training.py:134-141); final LayerNorm eps = 1e-6
  (reference run_vit_training.py:151)
- mean-pool over sequence (arXiv:2106.04560), then Linear head
  (reference run_vit_training.py:155-162)
- init: trunc-normal(std=0.02) weights, zero biases, LN ones/zeros (timm
  _init_vit_weights semantics, reference run_vit_training.py:125,142,152,128)
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from vitax.config import Config

Array = jax.Array
Dtype = Any

# timm _init_vit_weights: trunc_normal_(std=.02) on Linear weights, zero bias.
# jax's truncated_normal truncates at +/-2 sigma without rescaling the stddev —
# the same behavior as torch.nn.init.trunc_normal_ (measured std ~0.0176 for 0.02).
default_init = nn.initializers.truncated_normal(stddev=0.02)


class QuantDense(nn.Module):
    """nn.Dense's quantized-serving twin: kernel stored quantized (int8/fp8)
    with its per-output-channel float32 scale as the sibling `qscale` param.

    The serve engine merges consolidate.py's `__scale__/` arrays into the
    param tree under this name (vitax/serve/quant.py merge_quant_scales), so
    under `nn.scan` the stacked (L, 1, F) scales slice per layer exactly like
    the kernels. `quant_matmul` (vitax/ops/dequant_matmul.make_quant_matmul)
    owns the math — fused Pallas kernel or jnp reference, weight-only or
    int8 x int8 with dynamic activation quant; `act=False` sites (the head)
    stay weight-only always. Never used in training: `_dense` returns the
    byte-identical nn.Dense whenever quant_matmul is None."""

    features: int
    quant_matmul: Callable
    act: bool = True
    use_bias: bool = True
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: Array) -> Array:
        kernel = self.param("kernel", default_init,
                            (x.shape[-1], self.features), jnp.float32)
        qscale = self.param("qscale", nn.initializers.ones,
                            (1, self.features), jnp.float32)
        y = self.quant_matmul(x, kernel, qscale, act=self.act)
        y = y.astype(self.dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


def _dense(quant_matmul: Optional[Callable], act: bool, features: int, *,
           use_bias: bool = True, dtype, name: str):
    """The Dense constructor every matmul site below goes through: plain
    nn.Dense (training and full-precision serving — construction identical
    to the pre-quantization code, so the traced program is unchanged), or
    QuantDense under the SAME name when a quant_matmul is installed (param
    paths stay `<site>/kernel` etc. — no wrapper scope)."""
    if quant_matmul is None:
        return nn.Dense(
            features,
            use_bias=use_bias,
            dtype=dtype,
            param_dtype=jnp.float32,
            kernel_init=default_init,
            bias_init=nn.initializers.zeros,
            name=name,
        )
    return QuantDense(features=features, quant_matmul=quant_matmul, act=act,
                      use_bias=use_bias, dtype=dtype, name=name)


class PatchEmbed(nn.Module):
    """Conv patchify: (B, H, W, 3) -> (B, N, D). timm PatchEmbed equivalent
    (reference run_vit_training.py:124)."""

    patch_size: int
    embed_dim: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: Array) -> Array:
        p = self.patch_size
        x = nn.Conv(
            features=self.embed_dim,
            kernel_size=(p, p),
            strides=(p, p),
            padding="VALID",
            dtype=self.dtype,
            param_dtype=jnp.float32,
            kernel_init=default_init,
            bias_init=nn.initializers.zeros,
            name="proj",
        )(x)
        b, h, w, d = x.shape
        return x.reshape(b, h * w, d)


class Attention(nn.Module):
    """Multi-head self-attention with fused qkv projection (timm Attention parity:
    qkv_bias=True per reference run_vit_training.py:138).

    `attention_impl`, when provided, computes the (softmax(QK^T/sqrt(d))V) core —
    e.g. the Pallas flash-attention kernel — and receives (q, k, v) shaped
    (B, N, H, Dh). The default is the dense jnp path.
    """

    num_heads: int
    qkv_bias: bool = True
    att_dropout: float = 0.0
    proj_dropout: float = 0.0
    dtype: Dtype = jnp.bfloat16
    attention_impl: Optional[Callable[[Array, Array, Array], Array]] = None
    # NamedSharding anchor for the (B, N, 3D) qkv projection output. Without
    # it, a batch spanning 3 mesh axes (dp x fsdp x ep — the MoE meshes)
    # makes GSPMD keep the qkv weight fsdp-sharded instead of all-gathering
    # it (ZeRO-3), and the feature-sharded dot output then triggers
    # "involuntary full rematerialization" at this add (MULTICHIP_r03 tail).
    # Feature axis carries "tp" under tensor parallelism (Megatron layout).
    qkv_sharding: Optional[Any] = None
    quant_matmul: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        b, n, d = x.shape
        head_dim = d // self.num_heads

        qkv = _dense(
            self.quant_matmul, True, 3 * d,
            use_bias=self.qkv_bias,
            dtype=self.dtype,
            name="qkv",
        )(x)
        if self.qkv_sharding is not None:
            qkv = jax.lax.with_sharding_constraint(qkv, self.qkv_sharding)
        qkv = qkv.reshape(b, n, 3, self.num_heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # each (B, N, H, Dh)

        use_kernel = (
            self.attention_impl is not None
            and (self.att_dropout == 0.0 or deterministic)
        )
        drop_impl = getattr(self.attention_impl, "vitax_dropout", None)
        if use_kernel:
            out = self.attention_impl(q, k, v)  # (B, N, H, Dh)
        elif drop_impl is not None:
            # in-kernel attention dropout (vitax/ops/attention.py): the fused
            # path survives --att_dropout > 0. Flax's per-block rng splitting
            # (scan/pipeline) keys the mask: same (seed, step, layer) -> same
            # mask, matching nn.Dropout's determinism contract
            seed = jax.random.bits(self.make_rng("dropout"), (), jnp.uint32)
            out = drop_impl(q, k, v, seed)
        else:
            scale = head_dim ** -0.5
            # accumulate logits in float32 on the MXU for stable softmax
            attn = jnp.einsum(
                "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
            attn = jax.nn.softmax(attn, axis=-1).astype(self.dtype)
            attn = nn.Dropout(rate=self.att_dropout)(attn, deterministic=deterministic)
            out = jnp.einsum("bhqk,bkhd->bqhd", attn, v)

        out = out.reshape(b, n, d)
        out = _dense(
            self.quant_matmul, True, d,
            dtype=self.dtype,
            name="proj",
        )(out)
        out = nn.Dropout(rate=self.proj_dropout)(out, deterministic=deterministic)
        return out


class Mlp(nn.Module):
    """timm Mlp parity: Dense(hidden) -> GELU(exact) -> drop -> Dense(d) -> drop."""

    hidden_dim: int
    out_dim: int
    dropout: float = 0.0
    dtype: Dtype = jnp.bfloat16
    quant_matmul: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        x = _dense(
            self.quant_matmul, True, self.hidden_dim,
            dtype=self.dtype,
            name="fc1",
        )(x)
        x = nn.gelu(x, approximate=False)
        x = nn.Dropout(rate=self.dropout)(x, deterministic=deterministic)
        x = _dense(
            self.quant_matmul, True, self.out_dim,
            dtype=self.dtype,
            name="fc2",
        )(x)
        x = nn.Dropout(rate=self.dropout)(x, deterministic=deterministic)
        return x


class Block(nn.Module):
    """Pre-norm transformer block (timm Block parity, reference run_vit_training.py:134-141).

    moe_experts > 0 swaps the dense Mlp for the top-1-routed MoE MLP
    (vitax/models/moe.py) in EVERY block — homogeneous blocks keep the
    lax.scan stacking (and therefore pp partitioning) intact."""

    num_heads: int
    mlp_ratio: float = 4.0
    att_dropout: float = 0.0
    mlp_dropout: float = 0.0
    dtype: Dtype = jnp.bfloat16
    attention_impl: Optional[Callable] = None
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1
    moe_impl: str = "einsum"
    moe_ep_axis: Optional[str] = None   # manual-ep (pipeline body) only
    moe_ep_size: int = 1
    moe_dispatch_sharding: Optional[Any] = None
    token_sharding: Optional[Any] = None
    quant_matmul: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        d = x.shape[-1]
        if self.token_sharding is not None:
            # re-anchor the carry at every block entry: under the ep mesh the
            # MoE combine einsum hands the next block a partially-sharded
            # layout and the partitioner falls back to involuntary full
            # rematerialization at the qkv projection (MULTICHIP_r03 tail)
            x = jax.lax.with_sharding_constraint(x, self.token_sharding)
        qkv_sharding = None
        if self.token_sharding is not None:
            # qkv output anchor derived from the activation sharding: same
            # batch/token layout, feature over "tp" when tensor parallelism
            # is active (Megatron layout; the proj output returns to full)
            ts = self.token_sharding
            tp_ax = "tp" if ts.mesh.shape.get("tp", 1) > 1 else None
            qkv_sharding = NamedSharding(
                ts.mesh, P(ts.spec[0], ts.spec[1], tp_ax))
        # timm Block default norm_layer is nn.LayerNorm with eps=1e-5 when
        # constructed directly (as the reference does).
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32, name="norm1")(x)
        y = Attention(
            num_heads=self.num_heads,
            att_dropout=self.att_dropout,
            proj_dropout=self.mlp_dropout,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            qkv_sharding=qkv_sharding,
            quant_matmul=self.quant_matmul,
            name="attn",
        )(y, deterministic=deterministic)
        x = x + y
        y = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32, name="norm2")(x)
        if self.moe_experts > 0:
            from vitax.models.moe import MoeMlp
            y = MoeMlp(
                num_experts=self.moe_experts,
                hidden_dim=int(d * self.mlp_ratio),
                out_dim=d,
                capacity_factor=self.moe_capacity_factor,
                top_k=self.moe_top_k,
                impl=self.moe_impl,
                ep_axis=self.moe_ep_axis,
                ep_size=self.moe_ep_size,
                dtype=self.dtype,
                dispatch_sharding=self.moe_dispatch_sharding,
                token_sharding=self.token_sharding,
                name="moe",
            )(y, deterministic=deterministic)
        else:
            y = Mlp(
                hidden_dim=int(d * self.mlp_ratio),
                out_dim=d,
                dropout=self.mlp_dropout,
                dtype=self.dtype,
                quant_matmul=self.quant_matmul,
                name="mlp",
            )(y, deterministic=deterministic)
        return x + y


def _dots_and_attn_saveable(prim, *_, **__):
    """dots_saveable + fused-attention outputs: the Pallas attention core is a
    custom_vjp custom-call, NOT a dot_general, so under plain dots_saveable its
    forward kernel re-runs inside the rematted backward (profiled at ~10 ms/step
    on ViT-L/14 v5e — 3 attention call sites in the HLO instead of 2). Saving
    the custom_vjp outputs (o and the lse residual) skips that recompute for
    ~400 MB extra residency at the l14 bench shape."""
    # the fused core appears as `pallas_call` in the remat jaxpr (custom_vjp
    # is transparent there); shard_map-wrapped variants as `shard_map`
    return getattr(prim, "name", "") in (
        "dot_general", "pallas_call", "shard_map",
        "custom_vjp_call", "custom_vjp_call_jaxpr")


_REMAT_POLICIES = {
    # Save nothing per block — recompute everything in backward. This is the
    # reference's checkpoint_module semantics (torch activation checkpointing).
    "none_saveable": None,
    # Save MXU outputs (matmul results), recompute elementwise — often the best
    # HBM/FLOP tradeoff on TPU.
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    # dots + fused-attention (custom_vjp) outputs — skips the attention
    # forward-recompute in the rematted backward; fastest where it fits.
    "dots_attn_saveable": _dots_and_attn_saveable,
}


class VisionTransformer(nn.Module):
    """The full ViT (reference FSDPViTModel parity, run_vit_training.py:99-162),
    with blocks run as a scanned (stacked-parameter) stack."""

    image_size: int = 224
    patch_size: int = 14
    embed_dim: int = 5120
    num_heads: int = 32
    num_blocks: int = 32
    mlp_ratio: float = 4.0
    pos_dropout: float = 0.0
    att_dropout: float = 0.0
    mlp_dropout: float = 0.0
    num_classes: int = 1000
    dtype: Dtype = jnp.bfloat16
    scan_blocks: bool = True
    scan_unroll: int = 1
    grad_ckpt: bool = True
    remat_policy: str = "none_saveable"
    attention_impl: Optional[Callable] = None
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1
    moe_impl: str = "einsum"
    moe_ep_axis: Optional[str] = None   # manual-ep (pipeline body) only
    moe_ep_size: int = 1
    moe_dispatch_sharding: Optional[Any] = None
    # NamedSharding for (B, N, D) activations — anchors GSPMD batch sharding
    # and shards the token axis over "sp" for sequence parallelism
    token_sharding: Optional[Any] = None
    # serving-only: routes every Dense matmul (QKV/proj/MLP/head) through
    # the quantized path (vitax/ops/dequant_matmul.make_quant_matmul); None
    # keeps the exact nn.Dense program (training, full-precision serving)
    quant_matmul: Optional[Callable] = None

    def block_kwargs(self) -> dict:
        """Constructor kwargs for one transformer Block — shared between the
        scan/loop paths below and the pipeline-parallel stage function
        (vitax/parallel/pipeline.py), which applies detached Blocks against
        slices of the same stacked param tree."""
        return dict(
            num_heads=self.num_heads,
            mlp_ratio=self.mlp_ratio,
            att_dropout=self.att_dropout,
            mlp_dropout=self.mlp_dropout,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            moe_experts=self.moe_experts,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_top_k=self.moe_top_k,
            moe_impl=self.moe_impl,
            moe_ep_axis=self.moe_ep_axis,
            moe_ep_size=self.moe_ep_size,
            moe_dispatch_sharding=self.moe_dispatch_sharding,
            token_sharding=self.token_sharding,
            quant_matmul=self.quant_matmul,
        )

    @nn.compact
    def __call__(self, images: Array, deterministic: bool = True) -> Array:
        """images: (B, H, W, 3) float -> logits (B, num_classes) float32."""
        num_patches = (self.image_size // self.patch_size) ** 2

        x = PatchEmbed(
            patch_size=self.patch_size, embed_dim=self.embed_dim, dtype=self.dtype,
            name="patch_embed",
        )(images.astype(self.dtype))

        pos_embed = self.param(
            "pos_embed", default_init, (1, num_patches, self.embed_dim), jnp.float32)
        x = x + pos_embed.astype(self.dtype)
        x = nn.Dropout(rate=self.pos_dropout)(x, deterministic=deterministic)
        if self.token_sharding is not None:
            x = jax.lax.with_sharding_constraint(x, self.token_sharding)

        block_kwargs = self.block_kwargs()

        def body(block: Block, carry: Array, det: bool):
            return block(carry, det), None

        if self.grad_ckpt:
            policy = _REMAT_POLICIES[self.remat_policy]  # KeyError on unknown names
            # remat composed inside the scan body — per-block recompute, the
            # reference's checkpoint_module-then-FSDP order (run_vit_training.py:145).
            body = nn.remat(body, policy=policy, prevent_cse=False, static_argnums=(2,))

        if self.scan_blocks:
            # One compiled block body via lax.scan; params stacked with a leading
            # (num_blocks,) axis — uniform FSDP sharding and O(1) compile in depth.
            # unroll > 1 runs that many blocks per scan step: the per-block
            # dynamic-update-slice stacking constrains wgrad fusion layouts
            # (profiled 85-100 TF/s vs 164+ unconstrained on v5e), so giving
            # XLA a multi-block window recovers most of the fully-unrolled
            # throughput while keeping the stacked tree and O(L/unroll) compile.
            scan = nn.scan(
                body,
                # intermediates: per-layer sown values (the MoE aux loss)
                # stack along the layer axis like the params
                variable_axes={"params": 0, "intermediates": 0},
                split_rngs={"params": True, "dropout": True},
                length=self.num_blocks,
                in_axes=(nn.broadcast,),
                metadata_params={nn.meta.PARTITION_NAME: "layers"},
                unroll=min(self.scan_unroll, self.num_blocks),
            )
            x, _ = scan(Block(name="blocks", **block_kwargs), x, deterministic)
        else:
            for i in range(self.num_blocks):
                x, _ = body(Block(name=f"blocks_{i}", **block_kwargs), x, deterministic)

        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, param_dtype=jnp.float32, name="norm")(x)
        x = jnp.mean(x, axis=1)  # mean-pool over sequence (arXiv:2106.04560)
        if self.token_sharding is not None:
            # anchor the pooled (B, D) activations batch-sharded; the
            # constraint transposes onto the backward cotangent, where the
            # head-dot otherwise leaves D fsdp-sharded under 3-axis-batch
            # meshes and forces an involuntary full rematerialization
            ts = self.token_sharding
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(ts.mesh, P(ts.spec[0], None)))
        # head + loss in float32; the head site never act-quantizes (its f32
        # logits feed softmax directly — act=False in the quantized path)
        logits = _dense(
            self.quant_matmul, False, self.num_classes,
            dtype=jnp.float32,
            name="head",
        )(x)
        return logits


def apply_embed(p, images, *, patch_size: int, embed_dim: int, dtype):
    """Functional PatchEmbed + pos-embed application against an existing
    param tree — the pipeline paths (vitax/parallel/pipeline*.py) run the
    embed outside their shard_map and must match VisionTransformer.__call__
    exactly; keep in sync with the @nn.compact body above."""
    x = PatchEmbed(
        patch_size=patch_size, embed_dim=embed_dim, dtype=dtype,
    ).apply({"params": p["patch_embed"]}, images.astype(dtype))
    return x + p["pos_embed"].astype(dtype)


def apply_tail(p, x, *, num_classes: int, dtype):
    """Functional final-LayerNorm + mean-pool + head against an existing
    param tree (same keep-in-sync contract as apply_embed)."""
    x = nn.LayerNorm(
        epsilon=1e-6, dtype=dtype, param_dtype=jnp.float32,
    ).apply({"params": p["norm"]}, x)
    x = jnp.mean(x, axis=1)
    return nn.Dense(
        num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
    ).apply({"params": p["head"]}, x)


def make_windowed_forward(cfg: Config, model: "VisionTransformer"):
    """Functional scan forward with remat around GROUPS of --remat_window
    blocks instead of per block.

    The wgrad experiment for the profiled l14 ceiling (BASELINE.md): the
    per-block scan's saved residuals are written into (L, ...) stacked
    buffers by dynamic-update-slice each iteration, and the backward wgrad
    fusions co-writing those buffers run at 85-100 TF/s vs 164-182
    unconstrained. A group of w blocks saves its residuals ONCE per group
    (L/w stacking events) and gives XLA a w-block window to lay out wgrad
    fusions freely — like --scan_unroll, plus group-level checkpoint
    placement. Consumes the SAME stacked (L, ...) param tree (reshaped in
    the compute graph only — init and checkpoints are unchanged).

    v2 (round 5): composes with dropout (per-layer keys split from the step
    rng ride the scan as xs — same (seed, step) -> same masks, matching
    nn.Dropout's determinism contract) and with MoE (per-layer sown aux
    ingredients become scan ys, combined by aux_from_frac_prob exactly like
    the nn.scan path). pp remains excluded (config.validate; the pipeline
    path owns checkpoint placement there)."""
    w = cfg.remat_window
    groups = cfg.num_blocks // w
    block = Block(**model.block_kwargs())  # keeps the activation anchors
    policy = _REMAT_POLICIES[cfg.remat_policy]
    dtype = model.dtype
    moe = cfg.moe_experts > 0
    has_block_dropout = cfg.att_dropout > 0 or cfg.mlp_dropout > 0

    def forward(params, images, det: bool = True, rng=None,
                with_aux: bool = False):
        assert det or rng is not None, "training under dropout needs rng"
        p = params["params"]
        x = apply_embed(p, images, patch_size=cfg.patch_size,
                        embed_dim=cfg.embed_dim, dtype=dtype)
        if not det and cfg.pos_dropout > 0:
            pos_rng, rng = jax.random.split(rng)
            keep = jax.random.bernoulli(pos_rng, 1.0 - cfg.pos_dropout,
                                        x.shape)
            x = jnp.where(keep, x / (1.0 - cfg.pos_dropout),
                          jnp.zeros((), x.dtype))
        if model.token_sharding is not None:
            x = jax.lax.with_sharding_constraint(x, model.token_sharding)
        grouped = jax.tree.map(
            lambda l: l.reshape(groups, w, *l.shape[1:]), p["blocks"])
        use_keys = not det and has_block_dropout
        keys = (jax.random.split(rng, cfg.num_blocks).reshape(groups, w)
                if use_keys else None)

        def apply_group(carry, gparams, gkeys):
            aux = []
            for i in range(w):
                layer = jax.tree.map(lambda g: g[i], gparams)
                rngs = {"dropout": gkeys[i]} if use_keys else None
                if moe and with_aux:
                    carry, cols = block.apply(
                        {"params": layer}, carry, det, rngs=rngs,
                        mutable=["intermediates"])
                    m = cols["intermediates"]["moe"]
                    aux.append((m["moe_frac_tokens"][0],
                                m["moe_mean_prob"][0]))
                else:
                    carry = block.apply({"params": layer}, carry, det,
                                        rngs=rngs)
            if not aux:
                return carry, None
            return carry, (jnp.stack([a[0] for a in aux]),
                           jnp.stack([a[1] for a in aux]))  # (w, E) each

        body = jax.checkpoint(apply_group, policy=policy, prevent_cse=False,
                              static_argnums=())
        xs = (grouped, keys) if use_keys else (grouped,)
        x, aux_stacks = jax.lax.scan(
            lambda c, gx: body(c, *gx, *(() if use_keys else (None,))),
            x, xs)
        logits = apply_tail(p, x, num_classes=cfg.num_classes, dtype=dtype)
        if not with_aux:
            return logits
        fracs, probs = aux_stacks  # (groups, w, E) each
        if with_aux == "raw":
            # grad-accum microbatching needs the UNCOMBINED ingredients: the
            # load-balance product is taken after averaging them across
            # microbatches (vitax/train/step.py)
            return logits, ((fracs,), (probs,))
        from vitax.train.step import aux_from_frac_prob
        return logits, aux_from_frac_prob([fracs], [probs], cfg)

    return forward


def make_overlap_forward(cfg: Config, model: "VisionTransformer", mesh,
                         block_specs):
    """Functional scan forward with an explicit double-buffered gather
    schedule for the ZeRO-3 block params (--gather_overlap).

    The plain scan leaves each block's fsdp all-gather to GSPMD's use-site
    insertion, and XLA's latency-hiding scheduler cannot hoist a gather
    across a lax.scan iteration boundary — so on a pod the gather for block
    k serializes in front of block k's matmuls. Here the scan carry holds a
    PREFETCH SLOT: at iteration k the body consumes the already-gathered
    params for group k (fetched at k-1 via prefetch_gather, which pins the
    collective on the slot feeding the carry) and issues the gather for
    group k+1, overlapping it with group k's compute; group 0's gather is
    issued once before the scan. Groups are --remat_window blocks when the
    window is active, else single blocks.

    Gradients ride a custom_vjp around the group application, for two
    reasons measured on this exact structure:
    - carrying gathered (unsharded) params through a checkpointed scan body
      makes scan-AD stack them as (L, ...) residuals — the full unsharded
      model on every device, the ZeRO-3 memory bet inverted;
    - the ZeRO-3 backward must RE-gather each group's shards (that is what
      reshard_after_forward means), which plain remat only does as a side
      effect of recomputing through the use sites.
    The custom_vjp forward saves only (x, group index, the sharded stacked
    tree); its backward re-gathers the group explicitly, recomputes the
    group forward (none_saveable semantics — Config.validate pins the
    policy), and scatters the group's grads into a zeros-like stacked
    cotangent. The prefetched carry gets a zero cotangent: grads take the
    direct stacked-tree route, so the carry chain carries no gradient and
    AD never materializes a gathered tree it would have to keep.

    Dropout keys and the MoE aux ingredients thread through exactly like
    make_windowed_forward (same (seed, step) -> same masks; raw frac/prob
    stacks under with_aux == "raw"). pp is excluded (Config.validate)."""
    from vitax.parallel.sharding import prefetch_gather

    w = cfg.remat_window if cfg.remat_window > 1 else 1
    groups = cfg.num_blocks // w
    block = Block(**model.block_kwargs())  # keeps the activation anchors
    policy = _REMAT_POLICIES[cfg.remat_policy]
    dtype = model.dtype
    moe = cfg.moe_experts > 0
    has_block_dropout = cfg.att_dropout > 0 or cfg.mlp_dropout > 0

    def forward(params, images, det: bool = True, rng=None,
                with_aux: bool = False):
        assert det or rng is not None, "training under dropout needs rng"
        p = params["params"]
        x = apply_embed(p, images, patch_size=cfg.patch_size,
                        embed_dim=cfg.embed_dim, dtype=dtype)
        if not det and cfg.pos_dropout > 0:
            pos_rng, rng = jax.random.split(rng)
            keep = jax.random.bernoulli(pos_rng, 1.0 - cfg.pos_dropout,
                                        x.shape)
            x = jnp.where(keep, x / (1.0 - cfg.pos_dropout),
                          jnp.zeros((), x.dtype))
        if model.token_sharding is not None:
            x = jax.lax.with_sharding_constraint(x, model.token_sharding)
        stacked = p["blocks"]
        use_keys = not det and has_block_dropout
        collect_aux = moe and bool(with_aux)
        # raw uint32 key data (not typed key arrays): the keys cross a
        # custom_vjp boundary below, and integer leaves there take a None
        # cotangent cleanly
        key_data = (jax.random.key_data(
                        jax.random.split(rng, cfg.num_blocks)
                    ).reshape(groups, w, -1) if use_keys else None)

        def apply_group(carry, gparams, gkey_data):
            aux = []
            for i in range(w):
                layer = jax.tree.map(lambda g: g[i], gparams)
                rngs = ({"dropout": jax.random.wrap_key_data(gkey_data[i])}
                        if use_keys else None)
                if collect_aux:
                    carry, cols = block.apply(
                        {"params": layer}, carry, det, rngs=rngs,
                        mutable=["intermediates"])
                    m = cols["intermediates"]["moe"]
                    aux.append((m["moe_frac_tokens"][0],
                                m["moe_mean_prob"][0]))
                else:
                    carry = block.apply({"params": layer}, carry, det,
                                        rngs=rngs)
            if not aux:
                return carry, ()
            return carry, (jnp.stack([a[0] for a in aux]),
                           jnp.stack([a[1] for a in aux]))  # (w, E) each

        @jax.custom_vjp
        def run_group(x, gathered, g, gkey_data, stacked):
            del g, stacked  # forward consumes the PREFETCHED params only
            return apply_group(x, gathered, gkey_data)

        def run_group_fwd(x, gathered, g, gkey_data, stacked):
            # consumes the PREFETCHED params; `gathered` is deliberately NOT
            # a residual (a gathered-tree residual would stack to the full
            # unsharded model across scan iterations — see the docstring)
            out = apply_group(x, gathered, gkey_data)
            return out, (x, g, gkey_data, stacked)

        def run_group_bwd(res, ct):
            x, g, gkey_data, stacked = res
            with jax.named_scope("blocks_transpose_regather"):
                # ZeRO-3 backward semantics: re-gather the group's shards
                regathered = prefetch_gather(stacked, g * w, w, mesh,
                                             block_specs)
            # the recompute must run under a remat boundary: jax.checkpoint's
            # transpose wraps the recomputed values in optimization barriers,
            # which keeps XLA from fusing the recompute into its consumers and
            # re-rounding bf16 intermediates differently than the fwd program
            # did — without it the grads drift one bf16 ulp off the nn.scan
            # program's (measured; the fwd itself needs no barrier)
            regroup = jax.checkpoint(
                lambda x_, gp_: apply_group(x_, gp_, gkey_data),
                policy=policy, prevent_cse=False)
            _, vjp = jax.vjp(regroup, x, regathered)
            dx, dgp = vjp(ct)
            d_stacked = jax.tree.map(
                lambda full, d: jax.lax.dynamic_update_slice_in_dim(
                    jnp.zeros_like(full), d.astype(full.dtype), g * w,
                    axis=0),
                stacked, dgp)
            # zero cotangent for the prefetched carry: the gradient takes
            # the direct stacked-tree route, cutting the carry grad chain
            return (dx, jax.tree.map(jnp.zeros_like, regathered), None,
                    None, d_stacked)

        run_group.defvjp(run_group_fwd, run_group_bwd)

        def scan_body(carry, xs):
            x, gathered = carry
            g = xs[0]
            gkeys = xs[1] if use_keys else None
            with jax.named_scope("blocks_overlap"):
                x, aux = run_group(x, gathered, g, gkeys, stacked)
            # issue group g+1's gather now, so it overlaps group g+1's wait
            # with THIS group's compute; the final iteration re-fetches the
            # last group (in-bounds, result unused)
            nxt = jnp.minimum(g + 1, groups - 1)
            with jax.named_scope("blocks_prefetch"):
                gathered = prefetch_gather(stacked, nxt * w, w, mesh,
                                           block_specs)
            return (x, gathered), aux

        with jax.named_scope("prefetch_lead"):
            gathered0 = prefetch_gather(stacked, 0, w, mesh, block_specs)
        idx = jnp.arange(groups, dtype=jnp.int32)
        xs = (idx, key_data) if use_keys else (idx,)
        (x, _), aux_stacks = jax.lax.scan(
            scan_body, (x, gathered0), xs,
            unroll=min(cfg.scan_unroll, groups))
        logits = apply_tail(p, x, num_classes=cfg.num_classes, dtype=dtype)
        if not with_aux:
            return logits
        fracs, probs = aux_stacks  # (groups, w, E) each
        if with_aux == "raw":
            return logits, ((fracs,), (probs,))
        from vitax.train.step import aux_from_frac_prob
        return logits, aux_from_frac_prob([fracs], [probs], cfg)

    return forward


def build_model(cfg: Config, attention_impl: Optional[Callable] = None,
                token_sharding=None, moe_dispatch_sharding=None,
                quant_matmul: Optional[Callable] = None) -> VisionTransformer:
    """Construct the model from config (reference build_fsdp_vit_model parity,
    run_vit_training.py:165-200 — minus the wrapping, which in vitax is a sharding
    declaration applied at jit boundaries, not a module transform).

    `quant_matmul` (serving only) swaps every Dense site for QuantDense —
    see vitax/ops/dequant_matmul.make_quant_matmul."""
    return VisionTransformer(
        image_size=cfg.image_size,
        patch_size=cfg.patch_size,
        embed_dim=cfg.embed_dim,
        num_heads=cfg.num_heads,
        num_blocks=cfg.num_blocks,
        mlp_ratio=cfg.mlp_ratio,
        pos_dropout=cfg.pos_dropout,
        att_dropout=cfg.att_dropout,
        mlp_dropout=cfg.mlp_dropout,
        num_classes=cfg.num_classes,
        dtype=jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32,
        scan_blocks=cfg.scan_blocks,
        scan_unroll=cfg.scan_unroll,
        grad_ckpt=cfg.grad_ckpt,
        remat_policy=cfg.remat_policy,
        attention_impl=attention_impl,
        moe_experts=cfg.moe_experts,
        moe_capacity_factor=cfg.moe_capacity_factor,
        moe_top_k=cfg.moe_top_k,
        moe_impl=cfg.moe_impl,
        moe_dispatch_sharding=moe_dispatch_sharding,
        token_sharding=token_sharding,
        quant_matmul=quant_matmul,
    )


def count_params(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def expected_param_count(cfg: Config) -> int:
    """Closed-form parameter count, matching the reference's 10,077,917,160 at
    default flags (SURVEY.md section 6)."""
    d = cfg.embed_dim
    h = cfg.mlp_hidden_dim
    n = cfg.num_patches
    per_block = (
        d * 3 * d + 3 * d      # qkv
        + d * d + d            # proj
        + d * h + h            # fc1
        + h * d + d            # fc2
        + 2 * (2 * d)          # two LayerNorms
    )
    patch = 3 * cfg.patch_size * cfg.patch_size * d + d
    pos = n * d
    final_ln = 2 * d
    head = d * cfg.num_classes + cfg.num_classes
    return per_block * cfg.num_blocks + patch + pos + final_ln + head
