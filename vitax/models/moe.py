"""Mixture-of-Experts MLP with expert parallelism over the "ep" mesh axis.

Capability beyond the reference (SURVEY.md section 2.3 lists EP as absent —
the reference's ViT is dense). TPU-first formulation is the GShard/Switch
einsum form: routing produces a (tokens, experts, capacity) combine tensor,
dispatch and combine are einsums, and the expert weights carry a leading
(E, ...) dim sharded over "ep" (vitax/parallel/sharding.py). GSPMD then
inserts the batch<->expert all-to-alls from the shardings alone — no manual
collectives, same stance as the FSDP core. The "ep" mesh axis also carries
batch (vitax/parallel/mesh.py): dense params are replicated over it like dp,
expert weights stay local to their shard.

Design choices (Switch Transformer, arXiv:2101.03961):
- top-1 routing with probabilities in float32;
- static per-group capacity C = ceil(capacity_factor * N / E) (group = one
  sample's N tokens) — XLA-friendly static shapes; tokens over capacity are
  dropped (their MoE contribution is zero; the block residual passes them
  through);
- auxiliary load-balance loss E * sum_e(frac_tokens_e * mean_prob_e), sown
  into the "intermediates" collection and added to the CE loss with weight
  --moe_aux_weight (vitax/train/step.py).

Two dispatch/combine implementations (--moe_impl), MEASURED round 5:
- "einsum" (default): the GShard (B, N, E, C) one-hot form. The round-4
  profile blamed b16_moe's MFU gap (0.329 vs dense 0.490) on this band, but
  the gather alternative measured SLOWER on v5e — the one-hot matmuls map
  onto the MXU; TPU batched row-gathers/scatters do not. Round 5 builds the
  combine tensor directly in the activation dtype (identical numerics —
  disjoint top-2 slots never accumulate — at half the HBM bytes).
- "gather": integer scatter builds a per-slot source-token index (B, E*C),
  dispatch/combine are take_along_axis gathers, no (B, N, E, C) tensor
  exists. Measured b16_moe 477-527 img/s vs einsum's 617-650 across two
  layouts (BASELINE.md round-5 MoE section) — kept as the A/B arm and
  mutual oracle (tests/test_moe.py asserts gather == einsum on values and
  grads; trajectory tests pin both).
"""

from __future__ import annotations

import math

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from vitax.models.vit import Array, Dtype, default_init


class MoeMlp(nn.Module):
    """Drop-in replacement for the block Mlp: Dense->GELU->Dense per expert,
    top-1 routed. (B, N, D) -> (B, N, D)."""

    num_experts: int
    hidden_dim: int
    out_dim: int
    capacity_factor: float = 1.25
    top_k: int = 1                  # 1 = Switch; 2 = GShard-style top-2
    impl: str = "einsum"            # "einsum" (default) | "gather" (A/B arm)
    # manual expert parallelism (the pipeline body, where every batch axis is
    # already manual inside jax.shard_map and GSPMD cannot see the einsums):
    # ep_axis names the mesh axis; expert params are declared at their LOCAL
    # (E/ep_size, ...) shard shape and two tiled all_to_alls exchange
    # batch<->experts around the expert einsums — the hand-written form of
    # the a2a pair GSPMD derives from dispatch_sharding on the scan path.
    # The GLOBAL param tree keeps its (E, ...) shape (the shard_map in_specs
    # carry the "ep" placement), so checkpoints stay topology-independent.
    ep_axis: Optional[str] = None
    ep_size: int = 1
    dtype: Dtype = jnp.bfloat16
    # NamedSharding for the (E, B, C, D) dispatched tensor: P("ep", batch...)
    # anchors GSPMD so the dispatch/combine einsums lower to all-to-alls
    # instead of the partitioner's "involuntary full rematerialization"
    dispatch_sharding: Optional[Any] = None
    # NamedSharding for (B, N, D) activations: the combine einsum's output is
    # anchored back to the block's token layout so the residual add and the
    # next block see the batch-sharded form, not an expert-flavored remnant
    token_sharding: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        del deterministic  # no dropout inside the MoE MLP (v1)
        b, n, d = x.shape
        e = self.num_experts
        c = max(1, math.ceil(self.capacity_factor * n / e))  # static

        # --- router (float32 end to end: small and stability-critical) ---
        logits = nn.Dense(
            e, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=default_init, bias_init=nn.initializers.zeros,
            name="router",
        )(x.astype(jnp.float32))                      # (B, N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        gate1 = jnp.max(probs, axis=-1)               # (B, N)
        expert1 = jnp.argmax(probs, axis=-1)          # (B, N) int
        onehot1 = jax.nn.one_hot(expert1, e, dtype=jnp.float32)  # (B, N, E)

        # --- load-balance aux loss ingredients (Switch eq. 4-6; GShard uses
        # the same first-choice fractions under top-2). frac and prob are
        # sown SEPARATELY (not pre-multiplied into the aux scalar): they are
        # linear in the tokens, so per-microbatch means average exactly to
        # the full-batch means — the GPipe pipeline combines them across
        # microbatches before the nonlinear product and its aux matches the
        # scan path's bit-for-bit (vitax/parallel/pipeline.py,
        # vitax/train/step.py:aux_from_frac_prob) ---
        frac_tokens = jnp.mean(onehot1, axis=(0, 1))            # (E,)
        mean_prob = jnp.mean(probs, axis=(0, 1))                # (E,)
        self.sow("intermediates", "moe_frac_tokens", frac_tokens)
        self.sow("intermediates", "moe_mean_prob", mean_prob)

        # --- capacity assignment: slot = rank of the token among those
        # routed to the same expert within its (sample) group; under top-2,
        # ALL first choices rank before ALL second choices (GShard order) ---
        def slots_of(onehot, offset):
            position = jnp.cumsum(onehot, axis=1) * onehot      # (B, N, E)
            per_expert = position + offset * onehot             # rank incl. offset
            slot = (jnp.sum(per_expert, axis=-1) - 1.0).astype(jnp.int32)
            return slot, slot < c                               # (B, N) each

        def combine_of(gate, keep, onehot, slot):
            # combine[b, n, e, c] = gate at the token's (expert, slot).
            # Built directly in the ACTIVATION dtype: the old path built it
            # f32 and cast at the einsum — identical numerics (the gate
            # rounds to bf16 either way, and top-1/top-2 combines have
            # disjoint nonzero slots, so their sum never accumulates in
            # bf16) at HALF the HBM traffic on the largest MoE tensors
            # (the round-4 profile's 20.3% HBM-bound band).
            return ((gate * keep).astype(self.dtype)[:, :, None, None]
                    * onehot.astype(self.dtype)[:, :, :, None]
                    * jax.nn.one_hot(slot, c,
                                     dtype=self.dtype)[:, :, None, :])

        if self.top_k == 1:
            slot1, keep1 = slots_of(onehot1, 0.0)
            choices = [(gate1, keep1, expert1, onehot1, slot1)]
        else:
            assert self.top_k == 2, self.top_k
            probs2 = probs * (1.0 - onehot1)          # mask the first choice
            gate2 = jnp.max(probs2, axis=-1)
            expert2 = jnp.argmax(probs2, axis=-1)
            onehot2 = jax.nn.one_hot(expert2, e, dtype=jnp.float32)
            # renormalize the two gates (GShard: g_i = p_i / (p1 + p2))
            denom = gate1 + gate2 + 1e-9
            g1, g2 = gate1 / denom, gate2 / denom
            slot1, keep1 = slots_of(onehot1, 0.0)
            # second choices queue behind every first choice of that expert
            count1 = jnp.sum(onehot1, axis=1, keepdims=True)    # (B, 1, E)
            slot2, keep2 = slots_of(onehot2, count1)
            choices = [(g1, keep1, expert1, onehot1, slot1),
                       (g2, keep2, expert2, onehot2, slot2)]

        manual_ep = self.ep_axis is not None and self.ep_size > 1
        e_p = e // self.ep_size if manual_ep else e  # local expert shard
        w1 = self.param("w1", default_init, (e_p, d, self.hidden_dim), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e_p, self.hidden_dim), jnp.float32)
        w2 = self.param("w2", default_init, (e_p, self.hidden_dim, self.out_dim), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e_p, self.out_dim), jnp.float32)

        if self.impl == "gather":
            assert not manual_ep, (
                "--moe_impl gather does not implement the manual ep "
                "all-to-alls (pipeline body); use the einsum default "
                "(config.validate enforces this)")
            # the gather path stays in token-major (B, E, C, D) layout end
            # to end — a physical (E, B, C, D) transpose measured SLOWER
            # than the einsum oracle it was meant to beat (b16_moe 527 vs
            # 617 img/s on v5e); the expert einsums batch over B with the
            # expert dim in the middle instead
            xe = self._dispatch_gather(x, choices, e, c)        # (B, E, C, D)
            if self.dispatch_sharding is not None:
                xe = jax.lax.with_sharding_constraint(
                    xe, self._becd_sharding())
            h = jnp.einsum("becd,edh->bech", xe, w1.astype(self.dtype))
            h = h + b1.astype(self.dtype)[None, :, None, :]
            h = nn.gelu(h, approximate=False)
            ye = jnp.einsum("bech,eho->beco", h, w2.astype(self.dtype))
            ye = ye + b2.astype(self.dtype)[None, :, None, :]   # (B, E, C, D)
            if self.dispatch_sharding is not None:
                ye = jax.lax.with_sharding_constraint(
                    ye, self._becd_sharding())
            out = self._combine_gather(ye, choices, e, c)
        else:
            assert self.impl == "einsum", self.impl
            combine = sum(combine_of(g, k, oh, s)
                          for g, k, _, oh, s in choices)        # (B, N, E, C)
            dispatch = (combine > 0).astype(self.dtype)
            # dispatch -> per-expert batches (GShard einsum form)
            xe = jnp.einsum("bnec,bnd->ebcd", dispatch,
                            x.astype(self.dtype))               # (E, B, C, D)
            if self.dispatch_sharding is not None:
                xe = jax.lax.with_sharding_constraint(xe, self.dispatch_sharding)
            if manual_ep:
                # each shard dispatched its LOCAL batch to all E experts;
                # keep this shard's E/ep experts for the whole group's
                # batches: (E, B, C, D) -> (E/ep, B*ep, C, D)
                xe = jax.lax.all_to_all(xe, self.ep_axis, 0, 1, tiled=True)
            h = jnp.einsum("ebcd,edh->ebch", xe, w1.astype(self.dtype))
            h = h + b1.astype(self.dtype)[:, None, None, :]
            h = nn.gelu(h, approximate=False)
            ye = jnp.einsum("ebch,eho->ebco", h, w2.astype(self.dtype))
            ye = ye + b2.astype(self.dtype)[:, None, None, :]
            if manual_ep:
                # inverse exchange: back to (E, B, C, D) in original batch
                # order (autodiff transposes each a2a into its inverse)
                ye = jax.lax.all_to_all(ye, self.ep_axis, 1, 0, tiled=True)
            if self.dispatch_sharding is not None:
                ye = jax.lax.with_sharding_constraint(ye, self.dispatch_sharding)
            out = jnp.einsum("bnec,ebcd->bnd", combine, ye)
        if self.token_sharding is not None:
            out = jax.lax.with_sharding_constraint(out, self.token_sharding)
        return out

    def _becd_sharding(self):
        """dispatch_sharding is declared for the (E, B, C, D) einsum layout
        (P("ep"|None, batch, None, None)); the gather path's (B, E, C, D)
        layout swaps the first two entries."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        ds = self.dispatch_sharding
        return NamedSharding(ds.mesh, P(ds.spec[1], ds.spec[0],
                                        *ds.spec[2:]))

    # --- gather-based dispatch/combine ------------------------------------
    # A token's (expert, slot) pair is unique, so "which token fills slot
    # (e, c)" is a permutation fragment: scatter token indices (int32, no
    # feature dim) into a (B, E*C) source map, then move the D-wide data
    # with gathers. The backward of take_along_axis is a scatter-add over
    # the same unique indices — no (B, N, E, C) tensor in either direction.

    def _slot_ids(self, choices, e, c, n):
        """Per-choice flattened slot id (B, N): expert*C + slot for kept
        tokens; a unique out-of-range sentinel (E*C + token) for dropped
        ones so scatters can use mode="drop" + unique_indices soundly."""
        tok = jnp.arange(n, dtype=jnp.int32)[None, :]
        out = []
        for gate, keep, expert, _, slot in choices:
            flat = expert.astype(jnp.int32) * c + slot
            out.append((jnp.where(keep, flat, e * c + tok), gate, keep))
        return out

    def _dispatch_gather(self, x, choices, e, c):
        b, n, d = x.shape
        bidx = jnp.arange(b, dtype=jnp.int32)[:, None]
        tok = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (b, n))
        src = jnp.full((b, e * c), n, jnp.int32)
        for flat, _, _ in self._slot_ids(choices, e, c, n):
            # top-2 first/second choices occupy disjoint slots (the count1
            # offset), so sequential scatters never collide
            src = src.at[bidx, flat].set(tok, mode="drop", unique_indices=True)
        valid = src < n                                         # (B, E*C)
        xe = jnp.take_along_axis(x.astype(self.dtype),
                                 jnp.where(valid, src, 0)[:, :, None], axis=1)
        xe = jnp.where(valid[:, :, None], xe, jnp.zeros((), self.dtype))
        return xe.reshape(b, e, c, d)                           # (B, E, C, D)

    def _combine_gather(self, ye, choices, e, c):
        b = ye.shape[0]
        n = choices[0][0].shape[1]
        ye_flat = ye.reshape(b, e * c, ye.shape[-1])            # (B, E*C, D)
        out = jnp.zeros((b, n, ye.shape[-1]), self.dtype)
        for flat, gate, keep in self._slot_ids(choices, e, c, n):
            # dropped tokens carry an out-of-range sentinel: clamp the index
            # and zero the contribution through the keep-masked gate (the
            # einsum oracle's combine tensor is exactly gate*keep one-hot)
            y = jnp.take_along_axis(
                ye_flat, jnp.where(keep, flat, 0)[:, :, None], axis=1)
            out = out + (gate * keep).astype(self.dtype)[:, :, None] * y
        return out
