"""Mixture-of-Experts MLP with expert parallelism over the "ep" mesh axis.

Capability beyond the reference (SURVEY.md section 2.3 lists EP as absent —
the reference's ViT is dense). TPU-first formulation is the GShard/Switch
einsum form: routing produces a (tokens, experts, capacity) combine tensor,
dispatch and combine are einsums, and the expert weights carry a leading
(E, ...) dim sharded over "ep" (vitax/parallel/sharding.py). GSPMD then
inserts the batch<->expert all-to-alls from the shardings alone — no manual
collectives, same stance as the FSDP core. The "ep" mesh axis also carries
batch (vitax/parallel/mesh.py): dense params are replicated over it like dp,
expert weights stay local to their shard.

Design choices (Switch Transformer, arXiv:2101.03961):
- top-1 routing with probabilities in float32;
- static per-group capacity C = ceil(capacity_factor * N / E) (group = one
  sample's N tokens) — XLA-friendly static shapes; tokens over capacity are
  dropped (their MoE contribution is zero; the block residual passes them
  through);
- auxiliary load-balance loss E * sum_e(frac_tokens_e * mean_prob_e), sown
  into the "intermediates" collection and added to the CE loss with weight
  --moe_aux_weight (vitax/train/step.py).
"""

from __future__ import annotations

import math

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from vitax.models.vit import Array, Dtype, default_init


class MoeMlp(nn.Module):
    """Drop-in replacement for the block Mlp: Dense->GELU->Dense per expert,
    top-1 routed. (B, N, D) -> (B, N, D)."""

    num_experts: int
    hidden_dim: int
    out_dim: int
    capacity_factor: float = 1.25
    top_k: int = 1                  # 1 = Switch; 2 = GShard-style top-2
    dtype: Dtype = jnp.bfloat16
    # NamedSharding for the (E, B, C, D) dispatched tensor: P("ep", batch...)
    # anchors GSPMD so the dispatch/combine einsums lower to all-to-alls
    # instead of the partitioner's "involuntary full rematerialization"
    dispatch_sharding: Optional[Any] = None
    # NamedSharding for (B, N, D) activations: the combine einsum's output is
    # anchored back to the block's token layout so the residual add and the
    # next block see the batch-sharded form, not an expert-flavored remnant
    token_sharding: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        del deterministic  # no dropout inside the MoE MLP (v1)
        b, n, d = x.shape
        e = self.num_experts
        c = max(1, math.ceil(self.capacity_factor * n / e))  # static

        # --- router (float32 end to end: small and stability-critical) ---
        logits = nn.Dense(
            e, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=default_init, bias_init=nn.initializers.zeros,
            name="router",
        )(x.astype(jnp.float32))                      # (B, N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        gate1 = jnp.max(probs, axis=-1)               # (B, N)
        expert1 = jnp.argmax(probs, axis=-1)          # (B, N) int
        onehot1 = jax.nn.one_hot(expert1, e, dtype=jnp.float32)  # (B, N, E)

        # --- load-balance aux loss ingredients (Switch eq. 4-6; GShard uses
        # the same first-choice fractions under top-2). frac and prob are
        # sown SEPARATELY (not pre-multiplied into the aux scalar): they are
        # linear in the tokens, so per-microbatch means average exactly to
        # the full-batch means — the GPipe pipeline combines them across
        # microbatches before the nonlinear product and its aux matches the
        # scan path's bit-for-bit (vitax/parallel/pipeline.py,
        # vitax/train/step.py:aux_from_frac_prob) ---
        frac_tokens = jnp.mean(onehot1, axis=(0, 1))            # (E,)
        mean_prob = jnp.mean(probs, axis=(0, 1))                # (E,)
        self.sow("intermediates", "moe_frac_tokens", frac_tokens)
        self.sow("intermediates", "moe_mean_prob", mean_prob)

        # --- capacity assignment: slot = rank of the token among those
        # routed to the same expert within its (sample) group; under top-2,
        # ALL first choices rank before ALL second choices (GShard order) ---
        def slots_of(onehot, offset):
            position = jnp.cumsum(onehot, axis=1) * onehot      # (B, N, E)
            per_expert = position + offset * onehot             # rank incl. offset
            slot = (jnp.sum(per_expert, axis=-1) - 1.0).astype(jnp.int32)
            return slot, slot < c                               # (B, N) each

        def combine_of(gate, keep, onehot, slot):
            # combine[b, n, e, c] = gate at the token's (expert, slot)
            return ((gate * keep)[:, :, None, None]
                    * onehot[:, :, :, None]
                    * jax.nn.one_hot(slot, c,
                                     dtype=jnp.float32)[:, :, None, :])

        if self.top_k == 1:
            slot1, keep1 = slots_of(onehot1, 0.0)
            combine = combine_of(gate1, keep1, onehot1, slot1)  # (B, N, E, C)
        else:
            assert self.top_k == 2, self.top_k
            probs2 = probs * (1.0 - onehot1)          # mask the first choice
            gate2 = jnp.max(probs2, axis=-1)
            expert2 = jnp.argmax(probs2, axis=-1)
            onehot2 = jax.nn.one_hot(expert2, e, dtype=jnp.float32)
            # renormalize the two gates (GShard: g_i = p_i / (p1 + p2))
            denom = gate1 + gate2 + 1e-9
            g1, g2 = gate1 / denom, gate2 / denom
            slot1, keep1 = slots_of(onehot1, 0.0)
            # second choices queue behind every first choice of that expert
            count1 = jnp.sum(onehot1, axis=1, keepdims=True)    # (B, 1, E)
            slot2, keep2 = slots_of(onehot2, count1)
            combine = (combine_of(g1, keep1, onehot1, slot1)
                       + combine_of(g2, keep2, onehot2, slot2))
        dispatch = (combine > 0).astype(self.dtype)

        # --- dispatch -> per-expert batches -> combine (GShard einsums) ---
        xe = jnp.einsum("bnec,bnd->ebcd", dispatch,
                        x.astype(self.dtype))                   # (E, B, C, D)
        if self.dispatch_sharding is not None:
            xe = jax.lax.with_sharding_constraint(xe, self.dispatch_sharding)
        w1 = self.param("w1", default_init, (e, d, self.hidden_dim), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e, self.hidden_dim), jnp.float32)
        w2 = self.param("w2", default_init, (e, self.hidden_dim, self.out_dim), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e, self.out_dim), jnp.float32)
        h = jnp.einsum("ebcd,edh->ebch", xe, w1.astype(self.dtype))
        h = h + b1.astype(self.dtype)[:, None, None, :]
        h = nn.gelu(h, approximate=False)
        ye = jnp.einsum("ebch,eho->ebco", h, w2.astype(self.dtype))
        ye = ye + b2.astype(self.dtype)[:, None, None, :]       # (E, B, C, D)
        if self.dispatch_sharding is not None:
            ye = jax.lax.with_sharding_constraint(ye, self.dispatch_sharding)

        out = jnp.einsum("bnec,ebcd->bnd", combine.astype(self.dtype), ye)
        if self.token_sharding is not None:
            out = jax.lax.with_sharding_constraint(out, self.token_sharding)
        return out
