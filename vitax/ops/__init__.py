from vitax.ops.attention import (  # noqa: F401
    flash_attention,
    make_attention_impl,
    reference_attention,
)
