"""Fused clip+AdamW optimizer: one Pallas pass over the FSDP-sharded state.

The optax chain (`optax.chain(clip_by_global_norm, adamw)`) walks the full
param tree several times per step — a norm pass, a clip-scale pass, then ~10
elementwise HLO ops per leaf for the moment / bias-correction / decay /
param-step math — materializing multiple param-sized f32 temporaries exactly
where ZeRO-3 sharding is supposed to keep per-chip optimizer traffic minimal
(at 10B scale each avoided full-tree pass is ~40 GB of HBM per step).

This module replaces phase 2 of that pipeline with ONE kernel launch per
same-shape/dtype leaf group:

- **Phase 1** (plain jnp, fused by XLA with the grad tree): the single
  squared-norm reduction over all grad leaves. It emits the one clip scalar
  AND the `grad_norm` metric — the duplicated `optax.global_norm` the old
  step paid for the metric falls out for free.
- **Phase 2** (`fused_adamw_kernel`): per leaf, a Pallas kernel reads
  (param, grad, mu, nu) blocks plus the (clip_scale, lr, bias-correction)
  scalars from SMEM and writes (param, mu, nu) in place via
  `input_output_aliases` — clip-multiply, moment update, bias correction,
  decoupled weight decay, and the parameter step in a single pass over each
  element. Leaves sharing (2-D shape, dtype) share one compiled kernel (the
  blocks-stacked leaves are already grouped by construction), cached in
  `_pallas_leaf_call`.

Sharding: each leaf runs under `shard_map` with its own state spec, so every
chip touches only its FSDP shard — ZeRO semantics, `state_specs`, and the
donation contract are unchanged (the update is elementwise, so shard-local
math IS the global math once the clip scalar is computed globally).

Numerics match optax's `chain(clip_by_global_norm, adamw)` op-for-op (same
formulas, same operand order — see `_make_kernel`); the only intentional
deviation is the clip: optax scales per element as `(g / norm) * max_norm`,
the kernel multiplies by the precomputed scalar `max_norm / norm` (one
rounding each, ~1 ulp apart, and bit-identical whenever the clip does not
trigger). Off-TPU the kernel runs in Pallas interpret mode, exactly like
`vitax/ops/attention.py`; `VITAX_FORCE_MOSAIC=1` forces real Mosaic lowering
for AOT TPU-target compiles (tools/aot_topology.py).

The compiled-program invariant lives in vitax/analysis/rules.py VTX-R008:
interpret-mode Pallas leaves no custom-call marker in StableHLO, so the rule
reads the traced jaxpr, where every launch keeps `FUSED_KERNEL_NAME`.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from vitax.ops.attention import _interpret
from vitax.parallel.mesh import shard_map

PyTree = Any

# the jaxpr marker VTX-R008 greps for: pallas_call equations carry the kernel
# function's name in their printed params (one occurrence per launch)
FUSED_KERNEL_NAME = "fused_adamw_kernel"

# per-operand f32 block budget: 64K elements x 4 B x ~7 live buffers
# (p/g/mu/nu in + p/mu/nu out) ~ 1.8 MB of VMEM per grid step
_BLOCK_ELEMS = 64 * 1024


def fused_optimizer_active(cfg) -> bool:
    """Resolve --fused_optimizer {auto,on,off} for this process.

    `auto` engages the fused path exactly when the Pallas kernels lower to
    real Mosaic (TPU backend, or VITAX_FORCE_MOSAIC=1 for AOT TPU-target
    compiles) — mirroring the attention kernels' `_interpret()` policy, so
    default CPU programs stay on the reference optax chain. `on` forces the
    fused path anywhere (interpret mode off-TPU — the CI equivalence arms).

    Scenario exemptions (vitax/programs/registry.py): the fused kernel
    bypasses the optax chain and steps EVERY leaf at the schedule lr, so it
    cannot express the probe's masked-frozen backbone or the finetune
    backbone-lr multiplier — those tasks stay on optax regardless of mode
    (their validators reject an explicit `on`)."""
    task = getattr(cfg, "task", "train")
    if task == "probe":
        return False
    if task == "finetune" and getattr(cfg, "backbone_lr_mult", 1.0) != 1.0:
        return False
    mode = getattr(cfg, "fused_optimizer", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    return not _interpret()


def _as_2d(shape: Tuple[int, ...]) -> Tuple[int, int]:
    """Collapse a leaf shape to (rows, last-dim) for the kernel grid. The
    reshape is row-major contiguous — a bitcast to XLA, so it does not break
    the in-place aliasing chain."""
    if not shape:
        return (1, 1)
    n = shape[-1]
    m = 1
    for d in shape[:-1]:
        m *= d
    return (m, n)


def _make_kernel(b1: float, b2: float, eps: float, wd: float):
    def fused_adamw_kernel(scal_ref, p_ref, g_ref, mu_ref, nu_ref,
                           po_ref, muo_ref, nuo_ref):
        # scal (SMEM): [clip_scale, lr, 1-b1^t, 1-b2^t] — the only values
        # that vary per step; the hparams are compile-time constants
        s = scal_ref[0, 0]
        lr = scal_ref[0, 1]
        bc1 = scal_ref[0, 2]
        bc2 = scal_ref[0, 3]
        g = g_ref[...] * s
        # operand order matches optax.scale_by_adam's update_moment exactly
        mu = (1.0 - b1) * g + b1 * mu_ref[...]
        nu = (1.0 - b2) * (g * g) + b2 * nu_ref[...]
        upd = (mu / bc1) / (jnp.sqrt(nu / bc2) + eps) + wd * p_ref[...]
        po_ref[...] = p_ref[...] + (-lr) * upd
        muo_ref[...] = mu
        nuo_ref[...] = nu
    return fused_adamw_kernel


@functools.lru_cache(maxsize=None)
def _pallas_leaf_call(shape2d: Tuple[int, int], dtype: str,
                      hparams: Tuple[float, float, float, float],
                      interpret: bool):
    """One pallas_call per (2-D shape, dtype, hparams) leaf *group* — every
    leaf sharing these reuses the cached kernel (and XLA dedups the compiled
    custom-call). Writes (param, mu, nu) onto their input buffers via
    input_output_aliases."""
    m, n = shape2d
    bm = min(m, max(1, _BLOCK_ELEMS // max(n, 1)))
    if bm >= 8:
        bm -= bm % 8  # f32 sublane tile
    spec = pl.BlockSpec((bm, n), lambda i: (i, 0))
    return pl.pallas_call(
        _make_kernel(*hparams),
        grid=(pl.cdiv(m, bm),),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),  # scal (1, 4)
                  spec, spec, spec, spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((m, n), jnp.dtype(dtype))] * 3,
        # param <- param, mu <- mu, nu <- nu (operand 0 is the SMEM scalars)
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )


def _local_leaf_update(p, g, mu, nu, scal, *, hparams, interpret):
    """Shard-local fused update for one leaf (runs inside shard_map on
    multi-device meshes, so shapes here are the LOCAL shard shapes)."""
    m, n = _as_2d(p.shape)
    call = _pallas_leaf_call((m, n), str(p.dtype), hparams, interpret)
    po, muo, nuo = call(scal, p.reshape(m, n), g.reshape(m, n),
                        mu.reshape(m, n), nu.reshape(m, n))
    return po.reshape(p.shape), muo.reshape(p.shape), nuo.reshape(p.shape)


def find_adam_state(opt_state) -> optax.ScaleByAdamState:
    """Locate the single ScaleByAdamState in an optax chain state tree."""
    found: List[optax.ScaleByAdamState] = []

    def walk(s):
        if isinstance(s, optax.ScaleByAdamState):
            found.append(s)
        elif isinstance(s, tuple) and not hasattr(s, "_fields"):
            for x in s:
                walk(x)

    walk(opt_state)
    assert len(found) == 1, (
        f"expected exactly one ScaleByAdamState in the optimizer state, "
        f"found {len(found)} — the fused path only replaces the "
        f"clip+AdamW chain built by vitax.train.state.build_optimizer")
    return found[0]


def _rebuild_opt_state(s, new_adam: optax.ScaleByAdamState):
    """Reassemble the optax chain state: the AdamW moments swap in, and any
    other counted state (ScaleByScheduleState) increments exactly as its
    optax update_fn would — structure, dtypes, and sharding unchanged."""
    if isinstance(s, optax.ScaleByAdamState):
        return new_adam
    if isinstance(s, tuple) and hasattr(s, "_fields"):
        if "count" in s._fields:
            return s._replace(count=optax.safe_int32_increment(s.count))
        return s
    if isinstance(s, tuple):
        return tuple(_rebuild_opt_state(x, new_adam) for x in s)
    return s


def fused_clip_adamw(
    grads: PyTree,
    opt_state: PyTree,
    params: PyTree,
    *,
    grad_norm: jax.Array,
    schedule,
    clip_norm: float,
    weight_decay: float,
    b1: float,
    b2: float,
    eps: float,
    mesh=None,
    param_specs: Optional[PyTree] = None,
    interpret: Optional[bool] = None,
) -> Tuple[PyTree, PyTree]:
    """One-pass fused clip+AdamW update. Returns (new_params, new_opt_state)
    — a drop-in replacement for `tx.update` + `optax.apply_updates` on the
    chain built by vitax.train.state.build_optimizer, preserving the optax
    state structure (counts incremented, mu/nu replaced in place).

    `grad_norm` is the phase-1 global norm of `grads` (the caller computes it
    once and reuses it for the metric); `schedule` is the pure lr schedule
    evaluated at the pre-increment step count, exactly where optax's
    scale_by_schedule reads it. With `mesh`/`param_specs` set, every leaf
    updates under shard_map on its own spec — shard-local, no collectives."""
    if interpret is None:
        interpret = _interpret()
    adam = find_adam_state(opt_state)
    count_inc = optax.safe_int32_increment(adam.count)
    lr = jnp.asarray(schedule(adam.count), jnp.float32)
    bc1 = jnp.asarray(1 - b1 ** count_inc, jnp.float32)
    bc2 = jnp.asarray(1 - b2 ** count_inc, jnp.float32)
    if clip_norm and clip_norm > 0:
        clip_scale = jnp.where(grad_norm < clip_norm, jnp.float32(1.0),
                               clip_norm / grad_norm).astype(jnp.float32)
    else:
        clip_scale = jnp.float32(1.0)
    scal = jnp.stack([clip_scale, lr, bc1, bc2]).reshape(1, 4)

    hparams = (float(b1), float(b2), float(eps), float(weight_decay))
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    g_leaves = treedef.flatten_up_to(grads)
    mu_leaves = treedef.flatten_up_to(adam.mu)
    nu_leaves = treedef.flatten_up_to(adam.nu)
    specs = (treedef.flatten_up_to(param_specs) if param_specs is not None
             else [None] * len(p_leaves))

    sharded = mesh is not None and mesh.size > 1
    new_p, new_mu, new_nu = [], [], []
    for p, g, mu, nu, spec in zip(p_leaves, g_leaves, mu_leaves, nu_leaves,
                                  specs):
        fn = functools.partial(_local_leaf_update, hparams=hparams,
                               interpret=bool(interpret))
        if sharded and spec is not None:
            fn = shard_map(fn, mesh,
                           in_specs=(spec, spec, spec, spec, P()),
                           out_specs=(spec, spec, spec))
        po, muo, nuo = fn(p, g.astype(p.dtype), mu, nu, scal)
        new_p.append(po)
        new_mu.append(muo)
        new_nu.append(nuo)

    new_adam = optax.ScaleByAdamState(
        count=count_inc,
        mu=jax.tree_util.tree_unflatten(treedef, new_mu),
        nu=jax.tree_util.tree_unflatten(treedef, new_nu))
    return (jax.tree_util.tree_unflatten(treedef, new_p),
            _rebuild_opt_state(opt_state, new_adam))
