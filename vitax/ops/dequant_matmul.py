"""Fused dequant-matmul: quantized serve matmuls in one Pallas pass.

The PR-14 weight-only serve path dequantizes in-jit (`w_q.astype(f32) *
scale`, then a float dot): correct, but XLA materializes the dequantized
f32 weight as a real HBM tensor per matmul — at serve geometry that round
trip is the whole point of quantizing lost. This module is the serve twin
of vitax/ops/fused_optimizer.py: ONE blocked kernel per matmul that

- streams int8/fp8 weight blocks into VMEM and dequantizes them in
  registers (weight-only mode: f32 accumulation, per-output-channel scale
  applied AFTER the k-loop — exact, because the scale is constant along
  the contraction axis: ``(x @ (w*s))[i,j] == s[j] * (x @ w)[i,j]``);
- or, with dynamic activation quantization on, takes int8 activations
  (per-tensor absmax scale computed in-jit by `quantize_activations`) and
  runs the MXU's int8 x int8 path with an int32 accumulator, rescaling by
  ``act_scale * weight_scale`` once at the end.

No dequantized weight block ever exists outside VMEM — the VTX-R009
invariant (vitax/analysis/rules.py) pins both halves: the serve jaxpr must
launch `DEQUANT_KERNEL_NAME` and must not convert any weight-sized
quantized tensor to float outside a pallas_call.

Off-TPU the kernel runs in Pallas interpret mode, exactly like
vitax/ops/attention.py; `--fused_dequant {auto,on,off}` resolves through
`fused_dequant_active` (auto = real-Mosaic backends only). The unfused
fallbacks here are the reference semantics the kernel is pinned against
(tests/test_dequant_matmul.py, tools/check_kernels_on_chip.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vitax.ops.attention import _interpret

# the jaxpr marker VTX-R009 greps for: pallas_call equations carry the
# kernel function's name in their printed params (one occurrence per launch)
DEQUANT_KERNEL_NAME = "dequant_matmul_kernel"

# block caps: x (bm, bk) + w (bk, bn) + acc/out (bm, bn) stay well under
# ~0.5 MB of VMEM per grid step at int8 operand widths
_BM_CAP = 128
_BK_CAP = 512
_BN_CAP = 256


def fused_dequant_active(cfg) -> bool:
    """Resolve --fused_dequant {auto,on,off} for this process.

    `auto` engages the fused kernel exactly when serving quantized weights
    of a dense model on a real-Mosaic backend (TPU, or VITAX_FORCE_MOSAIC=1
    — the attention kernels' `_interpret()` policy), so CPU CI stays on the
    jnp reference path unless a test forces it. `on` forces the kernel
    anywhere (interpret mode off-TPU — the CI equivalence arms); MoE expert
    einsums are never routed through it."""
    mode = getattr(cfg, "fused_dequant", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    return (bool(getattr(cfg, "serve_quant_dtype", ""))
            and getattr(cfg, "moe_experts", 0) == 0
            and not _interpret())


def quantize_activations(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-tensor dynamic absmax quantization of activations to int8.

    Computed INSIDE the jitted forward (per batch — "dynamic"): one scalar
    scale per tensor keeps the rescale a cheap epilogue multiply, and the
    absmax guard maps all-zero tensors to scale 1.0 (they quantize and
    dequantize to 0). Returns (int8 values, float32 scalar scale)."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    sx = jnp.where(absmax == 0.0, jnp.float32(1.0),
                   absmax / jnp.float32(127.0))
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx),
                  -127, 127).astype(jnp.int8)
    return xq, sx


def _round_up(v: int, m: int) -> int:
    return ((v + m - 1) // m) * m


def _make_kernel(act: bool, nk: int):
    def dequant_matmul_kernel(sx_ref, x_ref, w_ref, s_ref, o_ref, acc_ref):
        k = pl.program_id(2)

        @pl.when(k == 0)
        def _zero():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        if act:
            # int8 x int8 on the MXU, int32 accumulator; both scales are
            # constant along k, so they factor out of the whole k-loop
            acc_ref[...] += jax.lax.dot_general(
                x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
        else:
            # dequantize the weight block in registers: int8/fp8 -> f32
            # never leaves VMEM (the channel scale is still the epilogue)
            acc_ref[...] += jax.lax.dot_general(
                x_ref[...], w_ref[...].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(k == nk - 1)
        def _write():
            o_ref[...] = (acc_ref[...].astype(jnp.float32)
                          * sx_ref[0, 0] * s_ref[...])
    return dequant_matmul_kernel


@functools.lru_cache(maxsize=None)
def _pallas_matmul_call(m: int, k: int, n: int, act: bool, w_dtype: str,
                        interpret: bool):
    """One pallas_call per (padded geometry, mode, weight dtype) — the serve
    engine's fixed buckets mean a handful of cache entries per model."""
    # quantized operands tile at (32, 128) on TPU, f32 at (8, 128); the
    # caller pads every dim to these multiples so blocks divide evenly
    bm = min(_BM_CAP, _round_up(m, 32 if act else 8))
    bk = min(_BK_CAP, _round_up(k, 128))
    bn = min(_BN_CAP, _round_up(n, 128))
    grid = (m // bm, n // bn, k // bk)  # k innermost: sequential on TPU
    acc_dtype = jnp.int32 if act else jnp.float32
    return pl.pallas_call(
        _make_kernel(act, grid[2]),
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),     # sx (1, 1)
                  pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
                  pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
                  pl.BlockSpec((1, bn), lambda i, j, kk: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        interpret=interpret,
    )


def _fused_2d(x2d, w, scale, sx, act: bool, interpret: bool):
    """Pad to tile multiples (zero padding is exact: padded k contributes
    x*0, padded m/n rows are sliced off) and launch the kernel."""
    m, k = x2d.shape
    n = w.shape[1]
    mp = _round_up(m, min(_BM_CAP, _round_up(m, 32 if act else 8)))
    kp = _round_up(k, min(_BK_CAP, _round_up(k, 128)))
    np_ = _round_up(n, min(_BN_CAP, _round_up(n, 128)))
    x2d = jnp.pad(x2d, ((0, mp - m), (0, kp - k)))
    w = jnp.pad(w, ((0, kp - k), (0, np_ - n)))
    scale = jnp.pad(scale.reshape(1, n).astype(jnp.float32),
                    ((0, 0), (0, np_ - n)))
    call = _pallas_matmul_call(mp, kp, np_, act, str(w.dtype), interpret)
    out = call(sx.reshape(1, 1), x2d, w, scale)
    return out[:m, :n]


def dequant_matmul(x: jax.Array, w: jax.Array, scale: jax.Array, *,
                   act: bool = False, fused: bool = True,
                   interpret: Optional[bool] = None) -> jax.Array:
    """``x @ (w * scale)`` for a quantized weight, without materializing the
    dequantized weight.

    `w` is an int8 or fp8 (K, F) kernel with per-output-channel float32
    `scale` broadcastable to (1, F); `x` keeps any leading batch dims.
    `act=True` additionally quantizes `x` per tensor and runs the matmul
    int8 x int8 (int8 weights only). `fused=False` is the jnp reference
    path — for act mode that is a PLAIN int8 dot_general, the lowering the
    activation-quant acceptance test pins via lower_bucket_mlir."""
    if interpret is None:
        interpret = _interpret()
    assert w.ndim == 2, f"dequant_matmul wants a 2-D kernel, got {w.shape}"
    if act:
        assert w.dtype == jnp.int8, (
            f"act-quant needs int8 weights (the other int8 operand), got "
            f"{w.dtype}")
    lead = x.shape[:-1]
    x2d = x.reshape(-1, x.shape[-1])
    if act:
        xq, sx = quantize_activations(x2d)
        if fused:
            out = _fused_2d(xq, w, scale, sx, True, bool(interpret))
        else:
            out = jax.lax.dot_general(
                xq, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32).astype(jnp.float32)
            out = out * sx * scale.reshape(1, -1)
    elif fused:
        out = _fused_2d(x2d.astype(jnp.float32), w, scale,
                        jnp.float32(1.0), False, bool(interpret))
    else:
        out = x2d.astype(jnp.float32) @ (w.astype(jnp.float32)
                                         * scale.reshape(1, -1))
    return out.reshape(*lead, w.shape[1])


def make_quant_matmul(cfg):
    """The quant_matmul closure vitax/models/vit.py QuantDense calls:
    resolves the act-quant and fused flags from cfg ONCE so the traced
    forward is static in both. `act=False` callers (the head — its f32
    output feeds softmax directly) stay weight-only even with act-quant
    on; eligibility lives at the call site."""
    act_mode = getattr(cfg, "serve_act_quant", "off") == "int8"
    fused = fused_dequant_active(cfg)

    def quant_matmul(x, w, scale, act=True):
        return dequant_matmul(x, w, scale, act=act_mode and act, fused=fused)
    return quant_matmul
