"""Fused attention kernels for TPU (Pallas).

The reference relies on timm's dense attention (materializes the (B,H,N,N)
score tensor in HBM; reference run_vit_training.py:134-141 via timm Block).
Here the softmax(QK^T/sqrt(d))V core is a Pallas kernel that keeps scores in
VMEM — one HBM round-trip for Q/K/V/O instead of score-tensor traffic — with a
custom VJP whose backward is also a fused kernel (flash-attention style
recompute from the saved logsumexp).

Design notes (see /opt/skills/guides/pallas_guide.md):
- grid = (batch, heads); each program computes one head's full (N, Dh)
  attention with scores in VMEM. ViT sequence lengths are short (256 tokens at
  224^2/patch 14), so whole-N blocks fit comfortably; beyond N = MAX_SEQ_IN_VMEM
  the streaming kernel (vitax/ops/flash_blocked.py, VMEM-independent of N) takes
  over, and ring attention handles cross-chip sequence sharding
  (vitax/parallel/ring_attention.py).
- logits accumulate in float32 on the MXU (preferred_element_type), softmax in
  float32, outputs cast back to the activation dtype.
- Under a multi-device mesh the kernel runs inside shard_map: batch over
  (dp, fsdp), heads over tp — attention is embarrassingly parallel in both, so
  no collectives are needed inside the kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

MAX_SEQ_IN_VMEM = 2048  # (N, N) f32 scores: 16 MB at 2048 — VMEM ceiling


def _interpret() -> bool:
    # run the kernels in Pallas interpret mode off-TPU (tests on CPU)
    return jax.devices()[0].platform != "tpu"


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Dense jnp attention core; (B, N, H, Dh) -> (B, N, H, Dh)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float):
    q = q_ref[0]  # (N, Dh)
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, 0][None, :]


def _fwd(q, k, v, scale):
    """q, k, v: (BH, N, Dh) -> (o (BH, N, Dh), lse (BH, N))."""
    bh, n, dh = q.shape
    spec = pl.BlockSpec((1, n, dh), lambda i: (i, 0, 0))
    lse_spec = pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale),
        grid=(bh,),
        in_specs=[spec, spec, spec],
        out_specs=[spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse[:, 0, :]


# ---------------------------------------------------------------------------
# backward kernel
# ---------------------------------------------------------------------------

def _bwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dlse_ref,
                dq_ref, dk_ref, dv_ref, *, scale: float):
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    o = o_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][0][:, None]    # (N, 1)
    dlse = dlse_ref[0][0][:, None]  # (N, 1) — lse cotangent (zeros when the
    # lse output is unused; nonzero under ring attention's logsumexp merge)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse)  # softmax probabilities, (N, N) f32

    dv = jax.lax.dot_general(  # P^T dO
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(  # dO V^T
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # (N, 1)
    # d lse_i / d s_ij = p_ij, so the lse cotangent adds dlse_i inside the parens
    ds = p * (dp - delta + dlse) * scale

    dq = jax.lax.dot_general(
        ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dk = jax.lax.dot_general(  # dS^T Q
        ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(scale, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    bh, n, dh = q.shape
    spec = pl.BlockSpec((1, n, dh), lambda i: (i, 0, 0))
    lse_spec = pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(bh,),
        in_specs=[spec, spec, spec, spec, lse_spec, spec, lse_spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((bh, n, dh), q.dtype)] * 3,
        interpret=_interpret(),
    )(q, k, v, o, lse[:, None, :], do, dlse[:, None, :])
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_bh_with_lse(q, k, v, scale):
    """(BH, N, Dh) fused attention returning (o, lse); differentiable in BOTH
    outputs — the lse cotangent feeds the backward kernel, which is what lets
    ring attention merge per-block kernel results with plain autodiff
    (vitax/parallel/ring_attention.py)."""
    return _fwd(q, k, v, scale)


def _flash_bh_lse_fwd(q, k, v, scale):
    o, lse = _fwd(q, k, v, scale)
    return (o, lse), (q, k, v, o, lse)


flash_bh_with_lse.defvjp(_flash_bh_lse_fwd, _bwd)


def _flash_bh(q, k, v, scale):
    return flash_bh_with_lse(q, k, v, scale)[0]


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Fused attention core; (B, N, H, Dh) -> (B, N, H, Dh), differentiable."""
    b, n, h, dh = q.shape
    scale = dh ** -0.5

    def to_bh(x):  # (B, N, H, Dh) -> (B*H, N, Dh)
        return x.transpose(0, 2, 1, 3).reshape(b * h, n, dh)

    o = _flash_bh(to_bh(q), to_bh(k), to_bh(v), scale)
    return o.reshape(b, h, n, dh).transpose(0, 2, 1, 3)


def _named(fn, name: str):
    """Tag an attention impl with a human-readable name for the startup log
    (shard_map outputs don't take attribute assignment, so wrap)."""
    def impl(q, k, v):
        return fn(q, k, v)
    impl.vitax_name = name
    return impl


def _tpu_kernel(cfg, n: int):
    """(kernel, name) for full-sequence attention on this platform, or
    (None, None) when only the dense jnp path applies. The single source of
    the use_flash_attention / platform / VMEM-threshold policy."""
    if not cfg.use_flash_attention:
        return None, None
    if jax.devices()[0].platform != "tpu":
        return None, None
    if n > MAX_SEQ_IN_VMEM:
        # streaming kernel: VMEM use independent of N (vitax/ops/flash_blocked.py)
        from vitax.ops.flash_blocked import blocked_flash_attention
        return blocked_flash_attention, "pallas streaming (blocked)"
    return flash_attention, "pallas fused (whole-N)"


def make_attention_impl(cfg, mesh: Optional[Mesh] = None):
    """Choose the attention core for this config/mesh:

    - sp > 1: sequence parallelism — ring attention (default), or Ulysses
      all-to-all head<->token resharding with --sp_impl ulysses when
      num_heads divides over sp*tp (vitax/parallel/{ring_attention,ulysses}.py)
    - TPU: the whole-N fused Pallas kernel, or the streaming (blocked) kernel
      beyond MAX_SEQ_IN_VMEM (shard_map-wrapped on multi-device meshes)
    - otherwise: None -> dense jnp path (GSPMD still shards batch/heads)
    """
    n = cfg.num_patches
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1

    if sp > 1:
        if n % sp != 0 or cfg.num_heads % tp != 0:
            return None  # indivisible: let GSPMD handle the dense path
        if getattr(cfg, "sp_impl", "ring") == "ulysses":
            if cfg.num_heads % (sp * tp) == 0:
                # all-to-all head<->token resharding; the inner kernel sees
                # the full sequence, so the Pallas cores apply on TPU
                from vitax.parallel.ulysses import make_ulysses_attention
                inner, _ = _tpu_kernel(cfg, n)
                return _named(make_ulysses_attention(mesh, inner),
                              "ulysses all-to-all (sp)")
            from vitax.utils.logging import master_print
            master_print(
                f"WARNING: --sp_impl ulysses needs num_heads divisible by "
                f"sp*tp ({cfg.num_heads} % {sp * tp} != 0); falling back to "
                f"ring attention")
        from vitax.parallel.ring_attention import make_ring_attention
        # local block product through the Pallas kernels on TPU (whole-N or
        # streaming by local length), dense jnp when kernels are disabled
        use_kernel = None if cfg.use_flash_attention else False
        return _named(make_ring_attention(mesh, use_kernel=use_kernel),
                      "ring attention (sp)")

    kernel, name = _tpu_kernel(cfg, n)
    if kernel is None:
        return None

    if mesh is None or mesh.size == 1:
        return _named(kernel, name)

    if cfg.num_heads % tp != 0:
        return None
    spec = P(("dp", "fsdp"), None, "tp", None)  # (B, N, H, Dh)
    return _named(jax.shard_map(
        kernel, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    ), name + " + shard_map")
