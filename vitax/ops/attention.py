"""Fused attention kernels for TPU (Pallas).

The reference relies on timm's dense attention (materializes the (B,H,N,N)
score tensor in HBM; reference run_vit_training.py:134-141 via timm Block).
Here the softmax(QK^T/sqrt(d))V core is a Pallas kernel that keeps scores in
VMEM — one HBM round-trip for Q/K/V/O instead of score-tensor traffic — with a
custom VJP whose backward is also a fused kernel (flash-attention style
recompute from the saved logsumexp).

Design notes (see /opt/skills/guides/pallas_guide.md):
- Two whole-N kernel families: the 4D-native kernel (default — operands
  viewed as (B, N, H*Dh), grid over (batch, head-groups), per-head lane
  slices, no HBM relayouts; measured +13% step throughput on ViT-L/14 v5e
  over the BH layout) and the BH kernel ((B*H, N, Dh), one head per program
  — the fallback when no head grouping fits VMEM, and the building block of
  ring attention's local products). ViT sequence lengths are short (256
  tokens at 224^2/patch 14), so whole-N blocks fit comfortably; beyond
  N = MAX_SEQ_IN_VMEM the streaming kernel (vitax/ops/flash_blocked.py,
  VMEM-independent of N) takes over, and ring attention handles cross-chip
  sequence sharding (vitax/parallel/ring_attention.py).
- logits accumulate in float32 on the MXU (preferred_element_type), softmax in
  float32, outputs cast back to the activation dtype.
- Under a multi-device mesh the kernel runs inside shard_map: batch over
  (dp, fsdp), heads over tp — attention is embarrassingly parallel in both, so
  no collectives are needed inside the kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

from vitax.parallel.mesh import BATCH_AXES, shard_map
from vitax.platform import backend_platform

MAX_SEQ_IN_VMEM = 2048  # (N, N) f32 scores: 16 MB at 2048 — VMEM ceiling


def _interpret() -> bool:
    # run the kernels in Pallas interpret mode off-TPU (tests on CPU).
    # VITAX_FORCE_MOSAIC=1 overrides: emit REAL Mosaic kernels regardless of
    # the host backend — for AOT compiles against TPU topology targets
    # (tools/aot_topology.py), where the host is CPU but the compile target
    # is a TPU and interpret-mode lowering would silently swap the
    # production kernels out of the program being proven.
    import os
    if os.environ.get("VITAX_FORCE_MOSAIC"):
        return False
    return backend_platform() != "tpu"


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Dense jnp attention core; (B, N, H, Dh) -> (B, N, H, Dh)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


# ---------------------------------------------------------------------------
# in-kernel dropout RNG
# ---------------------------------------------------------------------------
# Counter-based: the keep/drop decision for score element (b, h, q, k) is a
# pure uint32 hash of (seed, b*H+h, q, k) — murmur3's fmix32 finalizer over
# golden-ratio-multiplied coordinates. Plain vector uint32 ops, so the SAME
# code runs inside Mosaic kernels (this jax version's interpret mode lacks
# pltpu.prng_seed) and as host-side jnp — which is what makes the fwd kernel,
# the bwd kernel's mask RECOMPUTE (no (N, N) mask residual), and the test
# oracle (tests/test_attention.py) bit-identical by construction, on CPU and
# TPU alike. Reference behavior matched: timm's attn_drop on the softmax
# probabilities (reference run_vit_training.py:140,346 via timm Block).

_FMIX_C1 = 0x85EBCA6B
_FMIX_C2 = 0xC2B2AE35
_GOLD_Q = 0x9E3779B1   # odd multipliers decorrelate the raster counter
_GOLD_K = 0x85EBCA77
_GOLD_BH = 0xC2B2AE3D


def _fmix32(x):
    """murmur3 fmix32 finalizer (uint32 avalanche)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(_FMIX_C1)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(_FMIX_C2)
    x = x ^ (x >> 16)
    return x


def fold_shard_seed(mesh, axes, seed):
    """Fold the linearized shard position over `axes` into a dropout seed.

    Inside shard_map every shard sees the same LOCAL (batch, head) block
    indices, so without this two shards would draw identical masks; the
    fold gives each a decorrelated stream while staying deterministic given
    (seed, step). Shared by the shard_map dropout wrappers here and in
    vitax/parallel/ulysses.py — the mask-reproducibility contract (bwd
    regenerates the fwd's mask) requires exactly one fold idiom."""
    idx = jnp.uint32(0)
    for ax in axes:
        idx = (idx * jnp.uint32(mesh.shape[ax])
               + jax.lax.axis_index(ax).astype(jnp.uint32))
    return seed ^ _fmix32(idx * jnp.uint32(_GOLD_BH))


def dropout_keep_mask(seed, bh_index, nq: int, nk: int, rate: float,
                      transposed: bool = False, q0=0, k0=0):
    """f32 {0, 1} keep-mask for one (head, batch) score block.

    seed: traced uint32 scalar; bh_index: uint32 scalar identifying the
    global (batch, head) pair; transposed=True yields the (Nk, Nq) layout the
    4D kernel's transposed-score space uses — the SAME element decisions,
    so 4D and BH kernels drop identical (q, k) positions. q0/k0 offset the
    row/col indices to GLOBAL positions (may be traced scalars) — the
    streaming kernel's (q-block, k-block) tiles reproduce exactly the
    decisions the whole-(N, N) mask makes at those coordinates, which is
    what lets its bwd tiles regenerate the fwd's mask."""
    shape = (nk, nq) if transposed else (nq, nk)
    qdim, kdim = (1, 0) if transposed else (0, 1)
    qi = jax.lax.broadcasted_iota(jnp.uint32, shape, qdim) + jnp.uint32(q0)
    kj = jax.lax.broadcasted_iota(jnp.uint32, shape, kdim) + jnp.uint32(k0)
    x = (qi * jnp.uint32(_GOLD_Q) + kj * jnp.uint32(_GOLD_K)
         + bh_index.astype(jnp.uint32) * jnp.uint32(_GOLD_BH))
    bits = _fmix32(_fmix32(x ^ seed.astype(jnp.uint32)))
    # P(bits < T) = T / 2^32 = rate (T computed in python — exact, static)
    threshold = jnp.uint32(min(int(rate * 2 ** 32), 2 ** 32 - 1))
    return (bits >= threshold).astype(jnp.float32)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float):
    q = q_ref[0]  # (N, Dh)
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, 0][None, :]


def _fwd(q, k, v, scale):
    """q, k, v: (BH, N, Dh) -> (o (BH, N, Dh), lse (BH, N))."""
    bh, n, dh = q.shape
    spec = pl.BlockSpec((1, n, dh), lambda i: (i, 0, 0))
    lse_spec = pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale),
        grid=(bh,),
        in_specs=[spec, spec, spec],
        out_specs=[spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse[:, 0, :]


# ---------------------------------------------------------------------------
# backward kernel
# ---------------------------------------------------------------------------

def _bwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dlse_ref,
                dq_ref, dk_ref, dv_ref, *, scale: float):
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    o = o_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][0][:, None]    # (N, 1)
    dlse = dlse_ref[0][0][:, None]  # (N, 1) — lse cotangent (zeros when the
    # lse output is unused; nonzero under ring attention's logsumexp merge)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse)  # softmax probabilities, (N, N) f32

    # Matmul operands go in the INPUT dtype (bf16 under training) with f32
    # accumulation — f32 operands would run the MXU at half rate on v5e+
    # (profiled: the all-f32 version of this kernel was ~1.5x slower on l14);
    # softmax/score math above stays f32 for stability. With f32 inputs (tests)
    # the casts are no-ops and numerics are unchanged.
    pb = p.astype(q_ref.dtype)
    dob = do.astype(q_ref.dtype)
    dv = jax.lax.dot_general(  # P^T dO
        pb, dob, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(  # dO V^T
        dob, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # (N, 1) f32
    # d lse_i / d s_ij = p_ij, so the lse cotangent adds dlse_i inside the parens
    ds = (p * (dp - delta + dlse) * scale).astype(q_ref.dtype)

    dq = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dk = jax.lax.dot_general(  # dS^T Q
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    dq_ref[0] = dq.astype(dq_ref.dtype)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(scale, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    bh, n, dh = q.shape
    spec = pl.BlockSpec((1, n, dh), lambda i: (i, 0, 0))
    lse_spec = pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(bh,),
        in_specs=[spec, spec, spec, spec, lse_spec, spec, lse_spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((bh, n, dh), q.dtype)] * 3,
        interpret=_interpret(),
    )(q, k, v, o, lse[:, None, :], do, dlse[:, None, :])
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_bh_with_lse(q, k, v, scale):
    """(BH, N, Dh) fused attention returning (o, lse); differentiable in BOTH
    outputs — the lse cotangent feeds the backward kernel, which is what lets
    ring attention merge per-block kernel results with plain autodiff
    (vitax/parallel/ring_attention.py)."""
    return _fwd(q, k, v, scale)


def _flash_bh_lse_fwd(q, k, v, scale):
    o, lse = _fwd(q, k, v, scale)
    return (o, lse), (q, k, v, o, lse)


flash_bh_with_lse.defvjp(_flash_bh_lse_fwd, _bwd)


def _flash_bh(q, k, v, scale):
    return flash_bh_with_lse(q, k, v, scale)[0]


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Fused attention core; (B, N, H, Dh) -> (B, N, H, Dh), differentiable."""
    scale = q.shape[-1] ** -0.5
    return _from_bh(_flash_bh(_to_bh(q), _to_bh(k), _to_bh(v), scale), q.shape)


# ---------------------------------------------------------------------------
# 4D-native kernel: operates directly on (B, N, H, Dh) — no HBM transposes
# ---------------------------------------------------------------------------
# The BH kernels above need (B, N, H, Dh) -> (B*H, N, Dh) relayouts around
# every call; profiled at ~16 ms/step of pure HBM copies on ViT-L/14 v5e
# ("data formatting"). Here the operands are viewed as (B, N, H*Dh) — a free
# bitcast — the grid is (batch,), and each head is a static LANE slice of the
# block. Scores are computed in TRANSPOSED space (sT = K Q^T) so the per-head
# logsumexp is a (1, N) row — every slice/store stays a legal Mosaic layout
# (no vector transposes, no mid-tensor unit reshapes; probed 13% faster than
# the BH path forward on v5e).


def _fwd4_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, heads, scale,
                 pad_rows):
    dh = q_ref.shape[-1] // heads
    lse_rows = []
    for i in range(heads):  # static unroll: one (N, Dh) head per iteration
        q = q_ref[0][:, i * dh:(i + 1) * dh]
        k = k_ref[0][:, i * dh:(i + 1) * dh]
        v = v_ref[0][:, i * dh:(i + 1) * dh]
        sT = jax.lax.dot_general(  # (Nk, Nq)
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        m = jnp.max(sT, axis=0, keepdims=True)       # (1, Nq)
        p = jnp.exp(sT - m)
        l = jnp.sum(p, axis=0, keepdims=True)        # (1, Nq)
        o = jax.lax.dot_general(                     # (Nq, Dh)
            (p / l).astype(v.dtype), v, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_ref[0, :, i * dh:(i + 1) * dh] = o.astype(o_ref.dtype)
        lse_rows.append(m + jnp.log(l))
    if pad_rows:  # grouped-padded layout: block is (1, 1, P, Nq)
        n = q_ref.shape[1]
        lse_rows.append(jnp.zeros((pad_rows - heads, n), jnp.float32))
        lse_ref[0, 0] = jnp.concatenate(lse_rows, axis=0)  # (P, Nq)
    else:
        lse_ref[0] = jnp.concatenate(lse_rows, axis=0)     # (H, Nq)


def _bwd4_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref, dlse_ref,
                 dq_ref, dk_ref, dv_ref, *, heads, scale, pad_rows):
    dh = q_ref.shape[-1] // heads
    ones_row = jnp.ones((1, dh), jnp.float32)
    for i in range(heads):
        sl = slice(i * dh, (i + 1) * dh)
        q = q_ref[0][:, sl]                          # (Nq, Dh), input dtype
        k = k_ref[0][:, sl]
        v = v_ref[0][:, sl]
        o = o_ref[0][:, sl].astype(jnp.float32)
        do = do_ref[0][:, sl].astype(jnp.float32)
        lse_blk = lse_ref[0, 0] if pad_rows else lse_ref[0]
        dlse_blk = dlse_ref[0, 0] if pad_rows else dlse_ref[0]
        lse_row = lse_blk[i:i + 1, :]                # (1, Nq) f32
        dlse_row = dlse_blk[i:i + 1, :]

        sT = jax.lax.dot_general(                    # (Nk, Nq)
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        pT = jnp.exp(sT - lse_row)

        # matmuls take operands in the INPUT dtype with f32 accumulation —
        # f32 operands would run the MXU at half rate on v5e+; softmax/score
        # math stays f32 (with f32 inputs the casts are no-ops, so the
        # numerics tests compare exactly)
        pTb = pT.astype(q_ref.dtype)
        dob = do.astype(q_ref.dtype)
        dv = jax.lax.dot_general(                    # P^T dO: contract Nq
            pTb, dob, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # (Nk, Dh)
        dpT = jax.lax.dot_general(                   # V dO^T: contract Dh
            v, dob, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # (Nk, Nq)
        delta_row = jax.lax.dot_general(             # sum(dO*O, -1) as a row
            ones_row, do * o, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # (1, Nq)
        dsT = (pT * (dpT - delta_row + dlse_row) * scale).astype(q_ref.dtype)

        dq = jax.lax.dot_general(                    # dS K: contract Nk
            dsT, k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # (Nq, Dh)
        dk = jax.lax.dot_general(                    # dS^T Q: contract Nq
            dsT, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # (Nk, Dh)

        dq_ref[0, :, sl] = dq.astype(dq_ref.dtype)
        dk_ref[0, :, sl] = dk.astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv.astype(dv_ref.dtype)


# VMEM working-set estimate per program for the backward kernel (the larger
# one): 10 double-buffered (N, hb*Dh) blocks + per-head f32 score temps. The
# budget leaves Mosaic headroom of the ~16 MB/core.
_VMEM_BUDGET = 12 * 1024 * 1024


def _heads_per_program(n: int, h: int, dh: int, itemsize: int):
    """Head-group size: largest legal divisor of h fitting the VMEM budget,
    or None when no group does (the caller must then route the BH kernel).
    Legal = full-array blocks (hb == h), or the q/k/v/o block's lane dim
    hb*Dh is a multiple of 128 for a partial grid. Mosaic's OTHER tiling
    rule — the lse block's sublane dim must be a multiple of 8 — is
    satisfied by layout, not selection: groupings with hb % 8 != 0 store
    lse in the grouped-padded (B, H/hb, P, N) layout (_lse_pad_rows), whose
    (1, 1, P, N) blocks are always legal. (The sublane rule only bites on
    real TPU — interpret mode green-lit an illegal (1, 4, 256) lse block
    for h=32/dh=160, which the first on-chip 10b_slice compile caught.)"""
    for hb in range(h, 0, -1):
        if h % hb or not (hb == h or (hb * dh) % 128 == 0):
            continue
        est = 2 * 10 * n * hb * dh * itemsize + 4 * n * n * 4
        if est <= _VMEM_BUDGET:
            return hb
    return None  # even hb=1 busts the budget (large n: score temps dominate)


def _lse_pad_rows(hb: int, h: int) -> int:
    """Sublane padding P for the lse blocks of an hb-head grouping; 0 means
    the plain (B, H, N) layout with (1, hb, N) blocks is already legal
    (full-array coverage, or sublane dim a multiple of 8)."""
    if hb == h or hb % 8 == 0:
        return 0
    return -(-hb // 8) * 8  # round up to the f32 sublane tile


def flash4_supported(n: int, h: int, dh: int, itemsize: int) -> bool:
    """Whether the 4D-native kernel has a legal, VMEM-fitting head grouping
    for this shape — checked by _tpu_kernel before selecting it; the BH
    (relayout) kernel is the fallback (its per-(b,h) program holds ONE f32
    (N, N) score temp, so it survives to larger N)."""
    return _heads_per_program(n, h, dh, itemsize) is not None


def _fwd4(q, k, v, scale):
    b, n, h, dh = q.shape
    hb = _heads_per_program(n, h, dh, q.dtype.itemsize)
    assert hb is not None, (
        f"flash_attention_4d has no VMEM-fitting head grouping for "
        f"(n={n}, h={h}, dh={dh}) — gate on flash4_supported() first")
    pad = _lse_pad_rows(hb, h)
    q3, k3, v3 = (x.reshape(b, n, h * dh) for x in (q, k, v))  # free bitcasts
    spec = pl.BlockSpec((1, n, hb * dh), lambda i, j: (i, 0, j))
    if pad:  # grouped-padded lse: (B, H/hb, P, N) with full-tile blocks
        lse_spec = pl.BlockSpec((1, 1, pad, n), lambda i, j: (i, j, 0, 0))
        lse_shape = (b, h // hb, pad, n)
    else:
        lse_spec = pl.BlockSpec((1, hb, n), lambda i, j: (i, j, 0))
        lse_shape = (b, h, n)
    o, lse = pl.pallas_call(
        functools.partial(_fwd4_kernel, heads=hb, scale=scale, pad_rows=pad),
        grid=(b, h // hb),
        in_specs=[spec, spec, spec],
        out_specs=[spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, h * dh), q.dtype),
            jax.ShapeDtypeStruct(lse_shape, jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3)
    if pad:
        lse = lse[:, :, :hb, :].reshape(b, h, n)
    return o.reshape(b, n, h, dh), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash4_with_lse(q, k, v, scale):
    """(B, N, H, Dh) fused attention returning (o, lse (B, H, N));
    differentiable in both outputs (lse cotangent as in flash_bh_with_lse)."""
    return _fwd4(q, k, v, scale)


def _flash4_fwd(q, k, v, scale):
    o, lse = _fwd4(q, k, v, scale)
    return (o, lse), (q, k, v, o, lse)


def _flash4_bwd(scale, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    b, n, h, dh = q.shape
    hb = _heads_per_program(n, h, dh, q.dtype.itemsize)
    pad = _lse_pad_rows(hb, h)
    flat = (x.reshape(b, n, h * dh) for x in (q, k, v, o, do))
    q3, k3, v3, o3, do3 = flat
    spec = pl.BlockSpec((1, n, hb * dh), lambda i, j: (i, 0, j))
    if pad:  # re-pad (B, H, N) to the grouped layout the kernel blocks need
        def regroup(x):
            g = x.reshape(b, h // hb, hb, n)
            return jnp.pad(g, ((0, 0), (0, 0), (0, pad - hb), (0, 0)))
        lse, dlse = regroup(lse), regroup(dlse)
        lse_spec = pl.BlockSpec((1, 1, pad, n), lambda i, j: (i, j, 0, 0))
    else:
        lse_spec = pl.BlockSpec((1, hb, n), lambda i, j: (i, j, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd4_kernel, heads=hb, scale=scale, pad_rows=pad),
        grid=(b, h // hb),
        in_specs=[spec, spec, spec, spec, lse_spec, spec, lse_spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((b, n, h * dh), q.dtype)] * 3,
        interpret=_interpret(),
    )(q3, k3, v3, o3, lse, do3, dlse)
    return tuple(x.reshape(b, n, h, dh) for x in (dq, dk, dv))


flash4_with_lse.defvjp(_flash4_fwd, _flash4_bwd)


def flash_attention_4d(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Fused attention on native (B, N, H, Dh) layout — no HBM relayouts."""
    return flash4_with_lse(q, k, v, q.shape[-1] ** -0.5)[0]


# ---------------------------------------------------------------------------
# dropout variants: fused attention with in-kernel attention dropout
# ---------------------------------------------------------------------------
# The reference trains with timm's attn_drop on the softmax probabilities
# (run_vit_training.py:140,346). Until round 5, --att_dropout > 0 silently
# dropped *training* to the dense O(N^2) path (VERDICT r4 missing #3). Here
# the keep-mask is generated INSIDE the kernel from (seed, b*H+h, q, k) via
# dropout_keep_mask — the backward kernel regenerates it exactly (no (N, N)
# mask residual in HBM), mirroring the flash-attention lse-recompute trick.
#
# VJP under dropout: with probs = softmax(s), ms = mask/(1-r), a = probs*ms,
# o = a @ v:  dv = a^T do;  dprobs = (do v^T) * ms;  and since
# dot(dprobs, probs) = do . (a @ v) = do . o, the standard delta = sum(do*o)
# row STILL equals the softmax-vjp inner product — the only changes vs the
# dense-kernel backward are the two ms multiplications.


def _fwd_kernel_drop(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                     scale: float, rate: float):
    # seed_ref: (3,) uint32 SMEM — [seed, q0, k0]; the offsets shift the mask
    # to GLOBAL token coordinates (ring attention's per-shard blocks)
    q = q_ref[0]  # (N, Dh)
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    mask = dropout_keep_mask(seed_ref[0], jnp.uint32(pl.program_id(0)),
                             q.shape[0], k.shape[0], rate,
                             q0=seed_ref[1], k0=seed_ref[2])
    o = jax.lax.dot_general(
        (p * mask).astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0] = (o / (l * (1.0 - rate))).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l))[:, 0][None, :]


def _bwd_kernel_drop(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref,
                     dlse_ref, dq_ref, dk_ref, dv_ref, *, scale: float,
                     rate: float):
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    o = o_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][0][:, None]    # (N, 1)
    dlse = dlse_ref[0][0][:, None]  # (N, 1) — nonzero under ring's merge

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    probs = jnp.exp(s - lse)        # softmax probabilities, (N, N) f32
    ms = dropout_keep_mask(seed_ref[0], jnp.uint32(pl.program_id(0)),
                           q.shape[0], k.shape[0], rate,
                           q0=seed_ref[1], k0=seed_ref[2]) / (1.0 - rate)
    a = probs * ms                  # dropped/scaled probabilities

    ab = a.astype(q_ref.dtype)
    dob = do.astype(q_ref.dtype)
    dv = jax.lax.dot_general(  # A^T dO
        ab, dob, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(  # dO V^T
        dob, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # = dot(dprobs, probs)
    # d lse_i/d s_ij = probs_ij (the UNMASKED softmax — lse ignores dropout)
    ds = (probs * (dp * ms - delta + dlse) * scale).astype(q_ref.dtype)

    dq_ref[0] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[0] = jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _seed_spec():
    from jax.experimental.pallas import tpu as pltpu
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _seedvec(seed, q0=0, k0=0):
    """(3,) uint32 [seed, q0, k0] for the dropout kernels' SMEM input."""
    z = jnp.uint32
    return jnp.stack([seed.astype(jnp.uint32),
                      jnp.asarray(q0, jnp.int32).astype(z),
                      jnp.asarray(k0, jnp.int32).astype(z)])


def _fwd_bh_drop(q, k, v, seedvec, scale, rate):
    bh, n, dh = q.shape
    spec = pl.BlockSpec((1, n, dh), lambda i: (i, 0, 0))
    lse_spec = pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_drop, scale=scale, rate=rate),
        grid=(bh,),
        in_specs=[_seed_spec(), spec, spec, spec],
        out_specs=[spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(seedvec, q, k, v)
    return o, lse[:, 0, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_bh_dropout_lse(q, k, v, seedvec, scale, rate):
    """(BH, N, Dh) fused attention with attention dropout, returning
    (o, lse); differentiable in both outputs (the lse cotangent feeds the
    backward — ring attention's merge needs it). seedvec: (3,) uint32
    [seed, q0, k0] (_seedvec)."""
    return _fwd_bh_drop(q, k, v, seedvec, scale, rate)


def _flash_bh_drop_fwd(q, k, v, seedvec, scale, rate):
    o, lse = _fwd_bh_drop(q, k, v, seedvec, scale, rate)
    return (o, lse), (q, k, v, o, lse, seedvec)


def _flash_bh_drop_bwd(scale, rate, res, cts):
    import numpy as np
    q, k, v, o, lse, seedvec = res
    do, dlse = cts
    bh, n, dh = q.shape
    spec = pl.BlockSpec((1, n, dh), lambda i: (i, 0, 0))
    lse_spec = pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel_drop, scale=scale, rate=rate),
        grid=(bh,),
        in_specs=[_seed_spec(), spec, spec, spec, spec, lse_spec, spec,
                  lse_spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((bh, n, dh), q.dtype)] * 3,
        interpret=_interpret(),
    )(seedvec, q, k, v, o, lse[:, None, :], do, dlse[:, None, :])
    return dq, dk, dv, np.zeros(seedvec.shape, jax.dtypes.float0)


flash_bh_dropout_lse.defvjp(_flash_bh_drop_fwd, _flash_bh_drop_bwd)


def flash_bh_dropout(q, k, v, seed, scale, rate, q0=0, k0=0):
    """(BH, N, Dh) fused attention with attention dropout; seed is a traced
    uint32 scalar (fold the step/layer rng in before calling)."""
    return flash_bh_dropout_lse(q, k, v, _seedvec(seed, q0, k0),
                                scale, rate)[0]


def _fwd4_kernel_drop(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      heads, heads_total, scale, rate, pad_rows):
    dh = q_ref.shape[-1] // heads
    n = q_ref.shape[1]
    lse_rows = []
    for i in range(heads):
        q = q_ref[0][:, i * dh:(i + 1) * dh]
        k = k_ref[0][:, i * dh:(i + 1) * dh]
        v = v_ref[0][:, i * dh:(i + 1) * dh]
        sT = jax.lax.dot_general(  # (Nk, Nq)
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        m = jnp.max(sT, axis=0, keepdims=True)       # (1, Nq)
        p = jnp.exp(sT - m)
        l = jnp.sum(p, axis=0, keepdims=True)        # (1, Nq)
        # same (b*H + h) block index convention as the BH layout, so both
        # kernel families drop identical (q, k) positions for a given seed
        bh = (pl.program_id(0) * heads_total
              + pl.program_id(1) * heads + i)
        maskT = dropout_keep_mask(seed_ref[0], jnp.uint32(bh), n, n, rate,
                                  transposed=True, q0=seed_ref[1],
                                  k0=seed_ref[2])    # (Nk, Nq)
        o = jax.lax.dot_general(                     # (Nq, Dh)
            ((p * maskT) / (l * (1.0 - rate))).astype(v.dtype), v,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        o_ref[0, :, i * dh:(i + 1) * dh] = o.astype(o_ref.dtype)
        lse_rows.append(m + jnp.log(l))
    if pad_rows:
        lse_rows.append(jnp.zeros((pad_rows - heads, n), jnp.float32))
        lse_ref[0, 0] = jnp.concatenate(lse_rows, axis=0)
    else:
        lse_ref[0] = jnp.concatenate(lse_rows, axis=0)


def _bwd4_kernel_drop(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, do_ref,
                      dlse_ref, dq_ref, dk_ref, dv_ref, *, heads,
                      heads_total, scale, rate, pad_rows):
    dh = q_ref.shape[-1] // heads
    n = q_ref.shape[1]
    ones_row = jnp.ones((1, dh), jnp.float32)
    for i in range(heads):
        sl = slice(i * dh, (i + 1) * dh)
        q = q_ref[0][:, sl]
        k = k_ref[0][:, sl]
        v = v_ref[0][:, sl]
        o = o_ref[0][:, sl].astype(jnp.float32)
        do = do_ref[0][:, sl].astype(jnp.float32)
        lse_blk = lse_ref[0, 0] if pad_rows else lse_ref[0]
        dlse_blk = dlse_ref[0, 0] if pad_rows else dlse_ref[0]
        lse_row = lse_blk[i:i + 1, :]                # (1, Nq) f32
        dlse_row = dlse_blk[i:i + 1, :]

        sT = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        probsT = jnp.exp(sT - lse_row)               # (Nk, Nq)
        bh = (pl.program_id(0) * heads_total
              + pl.program_id(1) * heads + i)
        msT = dropout_keep_mask(seed_ref[0], jnp.uint32(bh), n, n, rate,
                                transposed=True, q0=seed_ref[1],
                                k0=seed_ref[2]) / (1.0 - rate)
        aT = probsT * msT

        aTb = aT.astype(q_ref.dtype)
        dob = do.astype(q_ref.dtype)
        dv = jax.lax.dot_general(                    # A^T dO: contract Nq
            aTb, dob, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # (Nk, Dh)
        dpT = jax.lax.dot_general(                   # V dO^T: contract Dh
            v, dob, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # (Nk, Nq)
        delta_row = jax.lax.dot_general(
            ones_row, do * o, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # (1, Nq)
        dsT = (probsT * (dpT * msT - delta_row + dlse_row)
               * scale).astype(q_ref.dtype)

        dq_ref[0, :, sl] = jax.lax.dot_general(
            dsT, k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_ref[0, :, sl] = jax.lax.dot_general(
            dsT, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv.astype(dv_ref.dtype)


def _fwd4_drop(q, k, v, seedvec, scale, rate):
    b, n, h, dh = q.shape
    hb = _heads_per_program(n, h, dh, q.dtype.itemsize)
    assert hb is not None, (n, h, dh)
    pad = _lse_pad_rows(hb, h)
    q3, k3, v3 = (x.reshape(b, n, h * dh) for x in (q, k, v))
    spec = pl.BlockSpec((1, n, hb * dh), lambda i, j: (i, 0, j))
    if pad:
        lse_spec = pl.BlockSpec((1, 1, pad, n), lambda i, j: (i, j, 0, 0))
        lse_shape = (b, h // hb, pad, n)
    else:
        lse_spec = pl.BlockSpec((1, hb, n), lambda i, j: (i, j, 0))
        lse_shape = (b, h, n)
    o, lse = pl.pallas_call(
        functools.partial(_fwd4_kernel_drop, heads=hb, heads_total=h,
                          scale=scale, rate=rate, pad_rows=pad),
        grid=(b, h // hb),
        in_specs=[_seed_spec(), spec, spec, spec],
        out_specs=[spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, h * dh), q.dtype),
            jax.ShapeDtypeStruct(lse_shape, jnp.float32),
        ],
        interpret=_interpret(),
    )(seedvec, q3, k3, v3)
    if pad:
        lse = lse[:, :, :hb, :].reshape(b, h, n)
    return o.reshape(b, n, h, dh), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash4_dropout_lse(q, k, v, seedvec, scale, rate):
    """(B, N, H, Dh) fused attention with in-kernel attention dropout,
    returning (o, lse (B, H, N)); differentiable in both outputs."""
    return _fwd4_drop(q, k, v, seedvec, scale, rate)


def _flash4_drop_fwd(q, k, v, seedvec, scale, rate):
    o, lse = _fwd4_drop(q, k, v, seedvec, scale, rate)
    return (o, lse), (q, k, v, o, lse, seedvec)


def _flash4_drop_bwd(scale, rate, res, cts):
    import numpy as np
    q, k, v, o, lse, seedvec = res
    do, dlse = cts
    b, n, h, dh = q.shape
    hb = _heads_per_program(n, h, dh, q.dtype.itemsize)
    pad = _lse_pad_rows(hb, h)
    flat = (x.reshape(b, n, h * dh) for x in (q, k, v, o, do))
    q3, k3, v3, o3, do3 = flat
    spec = pl.BlockSpec((1, n, hb * dh), lambda i, j: (i, 0, j))
    if pad:  # re-pad (B, H, N) to the grouped layout the kernel blocks need
        def regroup(x):
            g = x.reshape(b, h // hb, hb, n)
            return jnp.pad(g, ((0, 0), (0, 0), (0, pad - hb), (0, 0)))
        lse_in, dlse_in = regroup(lse), regroup(dlse)
        lse_spec = pl.BlockSpec((1, 1, pad, n), lambda i, j: (i, j, 0, 0))
    else:
        lse_in, dlse_in = lse, dlse
        lse_spec = pl.BlockSpec((1, hb, n), lambda i, j: (i, j, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd4_kernel_drop, heads=hb, heads_total=h,
                          scale=scale, rate=rate, pad_rows=pad),
        grid=(b, h // hb),
        in_specs=[_seed_spec(), spec, spec, spec, spec, lse_spec, spec,
                  lse_spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((b, n, h * dh), q.dtype)] * 3,
        interpret=_interpret(),
    )(seedvec, q3, k3, v3, o3, lse_in, do3, dlse_in)
    return (*(x.reshape(b, n, h, dh) for x in (dq, dk, dv)),
            np.zeros(seedvec.shape, jax.dtypes.float0))


flash4_dropout_lse.defvjp(_flash4_drop_fwd, _flash4_drop_bwd)


def flash4_dropout(q, k, v, seed, scale, rate, q0=0, k0=0):
    """(B, N, H, Dh) fused attention with in-kernel attention dropout."""
    return flash4_dropout_lse(q, k, v, _seedvec(seed, q0, k0),
                              scale, rate)[0]


def _tpu_dropout_kernel(cfg, n: int, force: bool = False,
                        local_heads: int = 0):
    """fn(q4, k4, v4, seed) -> o4 with in-kernel attention dropout at
    cfg.att_dropout (whole-N 4D/BH or streaming by shape), or None when
    kernels are disabled / off-TPU without force."""
    if not cfg.use_flash_attention or cfg.att_dropout <= 0.0:
        return None
    if not force and backend_platform() != "tpu":
        return None
    h = local_heads or cfg.num_heads
    dh = cfg.embed_dim // cfg.num_heads
    itemsize = 2 if cfg.dtype == "bfloat16" else 4
    path = _select_path(n, h, dh, itemsize)
    rate = float(cfg.att_dropout)
    if path == "4d":
        def drop4(q, k, v, seed):
            return flash4_dropout(q, k, v, seed, q.shape[-1] ** -0.5, rate)
        return drop4
    if path == "bh":
        def dropbh(q, k, v, seed):
            o = flash_bh_dropout(_to_bh(q), _to_bh(k), _to_bh(v), seed,
                                 q.shape[-1] ** -0.5, rate)
            return _from_bh(o, q.shape)
        return dropbh
    # streaming: the blocked kernels regenerate the same counter-hash mask
    # at global tile coordinates (vitax/ops/flash_blocked.py, round 5)
    from vitax.ops.flash_blocked import blocked_dropout_attention

    def dropstream(q, k, v, seed):
        return blocked_dropout_attention(q, k, v, seed, rate)
    return dropstream


def make_dense_dropout(rate: float):
    """Dense jnp full-sequence attention with the shared counter-hash dropout
    mask: (q, k, v, seed) -> o on (B, N, H, Dh). The off-TPU/kernels-disabled
    analog of _tpu_dropout_kernel — ring sp keeps a dense block product for
    the same purpose (_dense_block_drop); this gives the ulysses flavor the
    same anywhere-runnable dropout inner (ADVICE r5), with the same mask
    decisions at the same local (b*H + h, q, k) coordinates as the kernels
    (timm semantics: mask the softmax probabilities, rescale by 1/(1-rate))."""
    def dense_drop(q, k, v, seed):
        b, n, h, dh = q.shape
        scale = dh ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        p = jax.nn.softmax(s, axis=-1)
        bh = jnp.arange(b * h, dtype=jnp.uint32)
        mask = jax.vmap(
            lambda i: dropout_keep_mask(seed, i, n, n, rate))(bh)
        o = jnp.einsum("bhqk,bkhd->bqhd",
                       p * mask.reshape(b, h, n, n) / (1.0 - rate),
                       v.astype(jnp.float32))
        return o.astype(q.dtype)
    return dense_drop


def _select_path(n: int, h: int, dh: int, itemsize: int) -> str:
    """THE kernel-selection policy, shared by full-sequence dispatch
    (_tpu_kernel) and ring attention's local block products
    (block_kernel_with_lse): streaming past the VMEM sequence ceiling, 4D
    whole-N when a legal head grouping fits the budget, BH relayout
    otherwise (its whole-array blocks are always legal)."""
    if n > MAX_SEQ_IN_VMEM:
        return "streaming"
    if flash4_supported(n, h, dh, itemsize):
        return "4d"
    return "bh"


def block_kernel_with_lse(n: int, h: int, dh: int, itemsize: int):
    """Kernel for one (B, n, h, dh) attention block returning (o, lse (B,h,n)),
    differentiable in both outputs (the lse cotangent feeds the backward) —
    the with-lse variants of _select_path's cascade, used by ring attention.
    o comes back in the input dtype on every path — callers wanting f32
    accumulation (the logsumexp merge) must cast."""
    path = _select_path(n, h, dh, itemsize)
    if path == "4d":
        return flash4_with_lse
    if path == "streaming":
        from vitax.ops.flash_blocked import (
            DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, blocked_bh_with_lse)

        def streaming(q, k, v, scale):
            o, lse = blocked_bh_with_lse(
                _to_bh(q), _to_bh(k), _to_bh(v), scale,
                DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
            return _from_bh(o, q.shape), lse.reshape(q.shape[0], h, n)
        return streaming

    def bh(q, k, v, scale):
        o, lse = flash_bh_with_lse(_to_bh(q), _to_bh(k), _to_bh(v), scale)
        return _from_bh(o, q.shape), lse.reshape(q.shape[0], h, n)
    return bh


def block_dropout_kernel_with_lse(n: int, h: int, dh: int, itemsize: int):
    """Dropout analog of block_kernel_with_lse, for ring attention's local
    block products: kern(q, k, v, seedvec, scale, rate) -> (o, lse (B,h,n)),
    differentiable in both outputs. seedvec carries [seed, q0, k0] so the
    mask is evaluated at GLOBAL token coordinates — every ring step's block
    reproduces exactly the decisions the whole-(N, N) mask makes there,
    which is what makes ring dropout equal dense masked attention."""
    path = _select_path(n, h, dh, itemsize)
    if path == "4d":
        return flash4_dropout_lse
    if path == "streaming":
        from vitax.ops.flash_blocked import (
            DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, blocked_bh_dropout_lse)

        def streaming(q, k, v, seedvec, scale, rate):
            o, lse = blocked_bh_dropout_lse(
                _to_bh(q), _to_bh(k), _to_bh(v), seedvec, scale, rate,
                DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
            return _from_bh(o, q.shape), lse.reshape(q.shape[0], h, n)
        return streaming

    def bh(q, k, v, seedvec, scale, rate):
        o, lse = flash_bh_dropout_lse(_to_bh(q), _to_bh(k), _to_bh(v),
                                      seedvec, scale, rate)
        return _from_bh(o, q.shape), lse.reshape(q.shape[0], h, n)
    return bh


def _to_bh(x):  # (B, N, H, Dh) -> (B*H, N, Dh)
    b, n, h, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, n, dh)


def _from_bh(x, shape):  # (B*H, N, Dh) -> (B, N, H, Dh)
    b, n, h, dh = shape
    return x.reshape(b, h, n, dh).transpose(0, 2, 1, 3)


def _named(fn, name: str):
    """Tag an attention impl with a human-readable name for the startup log
    (shard_map outputs don't take attribute assignment, so wrap)."""
    def impl(q, k, v):
        return fn(q, k, v)
    impl.vitax_name = name
    return impl


def _tpu_kernel(cfg, n: int, force: bool = False, local_heads: int = 0):
    """(kernel, name) for full-sequence attention on this platform, or
    (None, None) when only the dense jnp path applies. The single source of
    the use_flash_attention / platform / VMEM-threshold policy.

    force=True skips the platform check (kernels run in Pallas interpret mode
    off-TPU) — used by the multichip dryrun so it exercises exactly this
    selection logic on the CPU mesh. local_heads is the PER-SHARD head count
    the kernel will actually see (num_heads/tp under shard_map, /(sp*tp)
    under Ulysses) — 4D-kernel support must be judged on that, not the
    global count."""
    if not cfg.use_flash_attention:
        return None, None
    if not force and backend_platform() != "tpu":
        return None, None
    h = local_heads or cfg.num_heads
    dh = cfg.embed_dim // cfg.num_heads
    itemsize = 2 if cfg.dtype == "bfloat16" else 4
    path = _select_path(n, h, dh, itemsize)
    if path == "streaming":
        # streaming kernel: VMEM use independent of N (vitax/ops/flash_blocked.py)
        from vitax.ops.flash_blocked import blocked_flash_attention
        return blocked_flash_attention, "pallas streaming (blocked)"
    if path == "4d":
        return flash_attention_4d, "pallas fused (4D whole-N)"
    # no legal VMEM-fitting head grouping (large N x D): the BH kernel's
    # per-(b,h) program holds a single (N, N) score temp and still fits
    return flash_attention, "pallas fused (whole-N, BH relayout)"


def make_attention_impl(cfg, mesh: Optional[Mesh] = None,
                        force_tpu_kernels: bool = False):
    """Choose the attention core for this config/mesh:

    - sp > 1: sequence parallelism — ring attention (default), or Ulysses
      all-to-all head<->token resharding with --sp_impl ulysses when
      num_heads divides over sp*tp (vitax/parallel/{ring_attention,ulysses}.py)
    - TPU: the whole-N fused Pallas kernel, or the streaming (blocked) kernel
      beyond MAX_SEQ_IN_VMEM (shard_map-wrapped on multi-device meshes)
    - otherwise: None -> dense jnp path (GSPMD still shards batch/heads)

    force_tpu_kernels=True makes the same selections off-TPU with the Pallas
    kernels in interpret mode (the multichip dryrun's production-path sweep).

    Attention dropout: every path that can run kernels runs dropout
    IN-KERNEL (exposed as impl.vitax_dropout, taking (q, k, v, seed)) — the
    whole-N and streaming kernels, the pipeline body (raw kernel on
    vitax_local_impl), ulysses sp (resharded inner kernel), and ring sp
    (global-coordinate masks per (q-shard, kv-block), which make the merged
    result equal dense masked attention) — each standalone AND inside the
    pipeline body. The sole dense-under-dropout surface is pp-under-tp
    (structural — warned below).
    """
    n = cfg.num_patches

    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1

    if cfg.use_flash_attention and cfg.att_dropout > 0.0:
        pp = getattr(cfg, "pp_size", 1)
        if pp > 1 and tp > 1:
            # the one remaining non-fused dropout surface: the pipeline
            # body under tp runs the dense einsum path for BOTH train and
            # eval (a Pallas kernel cannot ride a GSPMD-auto axis), so
            # dropout adds no further cliff there — but it is not fused.
            # (ring/ulysses sp — incl. under pp — and pp-without-tp all run
            # dropout in-kernel.)
            from vitax.utils.logging import master_print
            master_print(
                f"WARNING: --att_dropout {cfg.att_dropout} > 0 with the "
                f"pipeline body under tp runs unfused dense attention "
                f"(train AND eval — inherent to tp-in-pp, not to dropout). "
                f"Every kernel path (whole-N, streaming, ring/ulysses sp, "
                f"pp without tp) runs dropout fused.")

    if sp > 1:
        if n % sp != 0 or cfg.num_heads % tp != 0:
            return None  # indivisible: let GSPMD handle the dense path
        if getattr(cfg, "sp_impl", "ring") == "ulysses":
            if cfg.num_heads % (sp * tp) == 0:
                # all-to-all head<->token resharding; the inner kernel sees
                # the full sequence, so the Pallas cores apply on TPU
                from vitax.parallel.ulysses import (make_ulysses_attention,
                                                    make_ulysses_attention_pp,
                                                    make_ulysses_dropout)
                inner, _ = _tpu_kernel(cfg, n, force=force_tpu_kernels,
                                       local_heads=cfg.num_heads // (sp * tp))
                wrapped = _named(make_ulysses_attention(mesh, inner),
                                 "ulysses all-to-all (sp)")
                drop_inner = _tpu_dropout_kernel(
                    cfg, n, force=force_tpu_kernels,
                    local_heads=cfg.num_heads // (sp * tp))
                if drop_inner is None and cfg.att_dropout > 0.0:
                    # off-TPU / kernels disabled: dense inner with the same
                    # counter-hash mask, so BOTH sp flavors carry a dropout
                    # impl everywhere ring does — incl. the pp body at tp=1
                    # (ADVICE r5; ring's _dense_block_drop counterpart)
                    drop_inner = make_dense_dropout(float(cfg.att_dropout))
                if drop_inner is not None:
                    # sp with fused dropout (round 5): the resharded inner
                    # kernel runs the in-kernel mask on its full-sequence
                    # head slice (vitax/parallel/ulysses.py)
                    wrapped.vitax_dropout = make_ulysses_dropout(
                        mesh, drop_inner)
                # pp x sp: manualize only (sp, tp) inside the pipeline body
                wrapped.vitax_pp_impl = _named(
                    make_ulysses_attention_pp(inner, with_tp=tp > 1),
                    "ulysses all-to-all (sp, pp body)")
                if drop_inner is not None and tp == 1:
                    # pp x sp x dropout: the body's local a2a + dropout
                    # inner; the pipeline's per-(tick, layer, shard) keys
                    # provide the per-shard decorrelation
                    from vitax.parallel.ulysses import make_ulysses_dropout_pp
                    wrapped.vitax_pp_impl.vitax_dropout = (
                        make_ulysses_dropout_pp(drop_inner))
                return wrapped
            from vitax.utils.logging import master_print
            master_print(
                f"WARNING: --sp_impl ulysses needs num_heads divisible by "
                f"sp*tp ({cfg.num_heads} % {sp * tp} != 0); falling back to "
                f"ring attention")
        from vitax.parallel.ring_attention import (make_ring_attention,
                                                   make_ring_attention_pp,
                                                   make_ring_dropout)
        # local block product through the Pallas kernels on TPU (whole-N or
        # streaming by local length), dense jnp when kernels are disabled
        if not cfg.use_flash_attention:
            use_kernel = False
        else:
            use_kernel = True if force_tpu_kernels else None  # None = on-TPU
        wrapped = _named(make_ring_attention(mesh, use_kernel=use_kernel),
                         "ring attention (sp)")
        if cfg.att_dropout > 0.0:
            # ring dropout (round 5): global-coordinate masks per
            # (q-shard, kv-block) make the merged result equal dense masked
            # attention — works on both the kernel and dense block products
            wrapped.vitax_dropout = make_ring_dropout(
                mesh, float(cfg.att_dropout), use_kernel=use_kernel)
        # pp x sp: manualize only (sp, tp) inside the pipeline body
        wrapped.vitax_pp_impl = _named(
            make_ring_attention_pp(use_kernel=use_kernel, with_tp=tp > 1),
            "ring attention (sp, pp body)")
        if cfg.att_dropout > 0.0 and tp == 1:
            # pp x sp x dropout via the local ring body (seeded by the
            # pipeline's per-(tick, layer, shard) keys)
            from vitax.parallel.ring_attention import make_ring_dropout_pp
            wrapped.vitax_pp_impl.vitax_dropout = make_ring_dropout_pp(
                float(cfg.att_dropout), use_kernel=use_kernel)
        return wrapped

    if mesh is not None and mesh.size > 1 and cfg.num_heads % tp != 0:
        return None
    # under shard_map the kernel sees num_heads/tp heads per shard
    kernel, name = _tpu_kernel(cfg, n, force=force_tpu_kernels,
                               local_heads=cfg.num_heads // tp)
    if kernel is None:
        return None
    drop_kernel = _tpu_dropout_kernel(cfg, n, force=force_tpu_kernels,
                                      local_heads=cfg.num_heads // tp)

    if mesh is None or mesh.size == 1:
        impl = _named(kernel, name)
        if drop_kernel is not None:
            impl.vitax_dropout = drop_kernel
            # single-device impls also serve as the pipeline BODY impl
            # (vitax_local_impl path below is only built for mesh > 1);
            # inside the body the per-(tick, layer, shard) flax keys already
            # decorrelate masks, so the raw kernel applies as-is
        return impl
    spec = P(BATCH_AXES, None, "tp", None)  # (B, N, H, Dh)
    wrapped = _named(shard_map(
        kernel, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    ), name + " + shard_map")
    if drop_kernel is not None:
        shard_axes = tuple(a for a in (*BATCH_AXES, "tp")
                           if mesh.shape.get(a, 1) > 1)

        def drop_body(q, k, v, seed):
            return drop_kernel(q, k, v, fold_shard_seed(mesh, shard_axes,
                                                        seed))

        wrapped.vitax_dropout = shard_map(
            drop_body, mesh=mesh,
            in_specs=(spec, spec, spec, P()), out_specs=spec,
            check_vma=False,
        )
    # expose the unwrapped kernel for callers that run attention inside
    # their OWN shard_map (the pp pipeline body): when the mesh has no tp,
    # the body's operands are already fully local, so the raw kernel applies
    # (vitax_local_impl). Under tp > 1 no kernel variant is usable in the
    # body — vitax_pp_impl is explicitly None there (see below).
    wrapped.vitax_local_impl = _named(kernel, name)
    if drop_kernel is not None:
        # the RAW dropout kernel (no shard-index seed fold): inside the
        # pipeline body each (tick, layer, data-shard) draws its own flax
        # key (vitax/parallel/pipeline.py), so masks are already
        # decorrelated across shards — pp keeps the fused dropout path
        wrapped.vitax_local_impl.vitax_dropout = drop_kernel
    if mesh.shape.get("tp", 1) > 1:
        # pp body under tp: "tp" is a GSPMD-auto axis there and a Pallas
        # kernel cannot be auto-partitioned (and a nested tp shard_map hits
        # the jax-0.9 Shardy constant-hoisting bug — see
        # vitax/parallel/pipeline.py). None routes the Block to the dense
        # einsum path, which GSPMD partitions over the tp-global head dim.
        # MEASURED (round 5, v5e): at 10B dims the dense path costs ~1.9%
        # of step time (10b_slice 114.1 img/s dense vs 116.3 kernel at
        # matching knobs — BASELINE.md), so the unfused body is cheap at
        # flagship widths; the scan path keeps the kernel.
        wrapped.vitax_pp_impl = None
    else:
        wrapped.vitax_pp_impl = wrapped.vitax_local_impl
    return wrapped
