"""Blocked (streaming) flash attention for TPU — no whole-sequence VMEM limit.

The whole-N kernel in vitax/ops/attention.py keeps the full (N, N) score tile
in VMEM, which caps N at ~2048. This module streams KV blocks through VMEM with
the online-softmax recurrence (running max/sum), so VMEM use is
O(BQ*BK + BQ*Dh) regardless of N — the single-chip long-sequence path that
composes with cross-chip ring attention (vitax/parallel/ring_attention.py).
The reference has no long-sequence story at all (SURVEY.md section 5:
sequence length fixed at 256 tokens); this is capability beyond parity.

Kernel structure (see /opt/skills/guides/pallas_guide.md):
- forward: grid (BH, nq, nk), kv innermost/sequential; VMEM scratch carries
  the (BQ, Dh) accumulator and (BQ,) running max/sum across kv steps;
  @pl.when(k==0) resets, @pl.when(k==nk-1) finalizes o = acc/l and
  lse = m + log(l).
- backward: two kernels (no atomics on TPU) — dkv with grid (BH, nk, nq)
  accumulating dk/dv over q blocks, and dq with grid (BH, nq, nk); both
  recompute p = exp(s - lse) from the saved logsumexp, flash-style.
- inputs are padded to block multiples; invalid kv columns are masked to -inf
  before the softmax, padded q rows get lse=+inf so p==0 in the backward.
- logits/accumulators in float32 on the MXU (preferred_element_type), outputs
  cast back to the activation dtype.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vitax.ops.attention import _interpret, dropout_keep_mask

# jax < 0.5 names this TPUCompilerParams; same fields, renamed at 0.5
if not hasattr(pltpu, "CompilerParams"):
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e30  # large-but-finite: avoids inf-inf=nan in max/exp chains

"""Measured block defaults (round-5 ladder, tools/long_context_ladder.py ->
LADDER_LONGCTX.jsonl, v5e, ViT-L width train steps): the (512, 1024) pair
wins at N=4,096 (79.3 ms vs 102.8 at the untuned (512, 512)) and is within
5% of best at N=9,216 (295.9 vs 280.2 at (1024, 1024)). A taller K block
amortizes the online-softmax rescale chain over more of the KV stream."""
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def _col_mask(n_valid_ref, j, bk, s):
    """Mask (…, BK) score columns beyond the valid sequence length to NEG_INF."""
    n_valid = n_valid_ref[0]
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, s.ndim - 1) + j * bk
    return jnp.where(col < n_valid, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(n_valid_ref, seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale: float, bq: int, bk: int,
                nk: int, rate: float):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0]  # (BQ, Dh)
    k = k_ref[0]  # (BK, Dh)
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    s = _col_mask(n_valid_ref, j, bk, s)

    m_prev = m_ref[...]           # (BQ, 128) — col 0 is the live value
    l_prev = l_ref[...]
    m_cur = jnp.max(s, axis=-1, keepdims=True)           # (BQ, 1)
    m_new = jnp.maximum(m_prev, m_cur)                   # broadcast over 128 lanes
    alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])        # (BQ, 1)
    p = jnp.exp(s - m_new[:, :1])                        # (BQ, BK)
    # dropout drops NUMERATOR terms only (the keep-mask at GLOBAL (q, k)
    # coordinates); l accumulates the unmasked p — dense softmax-then-drop
    # semantics, same as the whole-N dropout kernels (vitax/ops/attention.py)
    l_new = alpha * l_prev[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
    if rate > 0.0:
        # seed_ref: (3,) uint32 [seed, q0_base, k0_base] — the bases shift
        # the whole mask to GLOBAL token coordinates (ring attention)
        p = p * dropout_keep_mask(
            seed_ref[0], jnp.uint32(pl.program_id(0)), bq, bk, rate,
            q0=seed_ref[1] + jnp.uint32(pl.program_id(1) * bq),
            k0=seed_ref[2] + jnp.uint32(j * bk))
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_new[:, :1], m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30) * (1.0 - rate)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:, :1] + jnp.log(jnp.maximum(
            l_ref[:, :1], 1e-30)))[:, 0][None, :]


def blocked_fwd_padded(q, k, v, n_valid, scale, bq, bk, seed=None,
                       rate: float = 0.0):
    """q,k,v: (BH, Np, Dh) padded to block multiples; returns (o, lse)."""
    bh, n_pad, dh = q.shape
    nq, nk = n_pad // bq, n_pad // bk
    if seed is None:
        seed = jnp.zeros((3,), jnp.uint32)
    qspec = pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0))
    lse_spec = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, bq=bq, bk=bk, nk=nk,
                          rate=rate),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # n_valid scalar
            pl.BlockSpec(memory_space=pltpu.SMEM),  # dropout seed scalar
            qspec, kspec, kspec,
        ],
        out_specs=[qspec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n_pad, dh), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, n_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, dh), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(n_valid, seed, q, k, v)
    return o, lse[:, 0, :]


# ---------------------------------------------------------------------------
# backward: dkv kernel (grid b, k-block, q-block) and dq kernel (b, q, k)
# ---------------------------------------------------------------------------

def _dkv_kernel(n_valid_ref, seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dlse_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                scale: float, bq: int, bk: int, nq: int, rate: float):
    jq = pl.program_id(2)

    @pl.when(jq == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = q_ref[0]                      # (BQ, Dh)
    k = k_ref[0]                      # (BK, Dh)
    v = v_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][0][:, None]      # (BQ, 1)
    delta = delta_ref[0][0][:, None]  # (BQ, 1)
    dlse = dlse_ref[0][0][:, None]    # (BQ, 1) — lse cotangent (ring merge)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    jk = pl.program_id(1)
    s = _col_mask(n_valid_ref, jk, bk, s)
    p = jnp.exp(s - lse)              # (BQ, BK); 0 for padded q rows (lse=+inf)

    if rate > 0.0:
        # regenerate the fwd's keep-mask at this tile's GLOBAL coordinates
        # (same VJP as the whole-N dropout kernels: delta = sum(do*o) still
        # equals the softmax-vjp inner product under the mask)
        ms = dropout_keep_mask(
            seed_ref[0], jnp.uint32(pl.program_id(0)), bq, bk, rate,
            q0=seed_ref[1] + jnp.uint32(jq * bq),
            k0=seed_ref[2] + jnp.uint32(jk * bk)) / (1.0 - rate)
        a = p * ms
    else:
        a = p
    dv_acc[...] += jax.lax.dot_general(  # A^T dO
        a, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(            # dO V^T
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if rate > 0.0:
        dp = dp * ms
    ds = p * (dp - delta + dlse) * scale  # d lse_i/d s_ij = p_ij
    dk_acc[...] += jax.lax.dot_general(  # dS^T Q
        ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jq == nq - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _dq_kernel(n_valid_ref, seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dlse_ref, dq_ref, dq_acc, *, scale: float, bq: int,
               bk: int, nk: int, rate: float):
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][0][:, None]
    delta = delta_ref[0][0][:, None]
    dlse = dlse_ref[0][0][:, None]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
    s = _col_mask(n_valid_ref, jk, bk, s)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if rate > 0.0:
        dp = dp * (dropout_keep_mask(
            seed_ref[0], jnp.uint32(pl.program_id(0)), bq, bk, rate,
            q0=seed_ref[1] + jnp.uint32(pl.program_id(1) * bq),
            k0=seed_ref[2] + jnp.uint32(jk * bk)) / (1.0 - rate))
    ds = p * (dp - delta + dlse) * scale
    dq_acc[...] += jax.lax.dot_general(
        ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def blocked_bwd_padded(q, k, v, o, lse, do, dlse, n_valid, scale, bq, bk,
                       seed=None, rate: float = 0.0):
    bh, n_pad, dh = q.shape
    nq, nk = n_pad // bq, n_pad // bk
    if seed is None:
        seed = jnp.zeros((3,), jnp.uint32)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]  # (BH, 1, Np)
    lse3 = lse[:, None, :]
    dlse3 = dlse[:, None, :]

    qspec_q = pl.BlockSpec((1, bq, dh), lambda b, jk, jq: (b, jq, 0))
    kspec_k = pl.BlockSpec((1, bk, dh), lambda b, jk, jq: (b, jk, 0))
    row_q = pl.BlockSpec((1, 1, bq), lambda b, jk, jq: (b, 0, jq))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, bq=bq, bk=bk, nq=nq,
                          rate=rate),
        grid=(bh, nk, nq),
        in_specs=[smem, smem,
                  qspec_q, kspec_k, kspec_k, qspec_q, row_q, row_q, row_q],
        out_specs=[kspec_k, kspec_k],
        out_shape=[jax.ShapeDtypeStruct((bh, n_pad, dh), q.dtype)] * 2,
        scratch_shapes=[pltpu.VMEM((bk, dh), jnp.float32),
                        pltpu.VMEM((bk, dh), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(n_valid, seed, q, k, v, do, lse3, delta, dlse3)

    qspec = pl.BlockSpec((1, bq, dh), lambda b, jq, jk: (b, jq, 0))
    kspec = pl.BlockSpec((1, bk, dh), lambda b, jq, jk: (b, jk, 0))
    row = pl.BlockSpec((1, 1, bq), lambda b, jq, jk: (b, 0, jq))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, bq=bq, bk=bk, nk=nk,
                          rate=rate),
        grid=(bh, nq, nk),
        in_specs=[smem, smem,
                  qspec, kspec, kspec, qspec, row, row, row],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, n_pad, dh), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(n_valid, seed, q, k, v, do, lse3, delta, dlse3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# padding wrapper + custom VJP
# ---------------------------------------------------------------------------

def _pad_len(n: int, block: int) -> int:
    return (n + block - 1) // block * block


def _pad_seq(x, n_pad):
    n = x.shape[1]
    if n == n_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, n_pad - n), (0, 0)))


def _blocked_fwd_impl(q, k, v, scale, bq, bk, seed=None, rate=0.0):
    n = q.shape[1]
    n_pad = _pad_len(n, math.lcm(bq, bk))  # both grids must tile evenly
    n_valid = jnp.asarray([n], jnp.int32)
    o, lse = blocked_fwd_padded(
        _pad_seq(q, n_pad), _pad_seq(k, n_pad), _pad_seq(v, n_pad),
        n_valid, scale, bq, bk, seed=seed, rate=rate)
    return o[:, :n], lse[:, :n]


def _blocked_bwd_impl(q, k, v, o, lse, do, dlse, scale, bq, bk, seed=None,
                      rate=0.0):
    n = q.shape[1]
    n_pad = _pad_len(n, math.lcm(bq, bk))
    n_valid = jnp.asarray([n], jnp.int32)
    pad = n_pad - n
    # padded q rows: lse=+inf makes p=exp(s-lse)=0, do=0 kills dv terms
    lse_p = jnp.pad(lse, ((0, 0), (0, pad)), constant_values=jnp.inf)
    dlse_p = jnp.pad(dlse, ((0, 0), (0, pad)))
    dq, dk, dv = blocked_bwd_padded(
        _pad_seq(q, n_pad), _pad_seq(k, n_pad), _pad_seq(v, n_pad),
        _pad_seq(o, n_pad), lse_p, _pad_seq(do, n_pad), dlse_p,
        n_valid, scale, bq, bk, seed=seed, rate=rate)
    return dq[:, :n], dk[:, :n], dv[:, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def blocked_bh_with_lse(q, k, v, scale, bq, bk):
    """(BH, N, Dh) streaming attention returning (o, lse); differentiable in
    both outputs (the lse cotangent feeds the backward kernels) — composes with
    ring attention's logsumexp merge for local blocks beyond the whole-N
    kernel's VMEM ceiling."""
    return _blocked_fwd_impl(q, k, v, scale, bq, bk)


def _blocked_bh_fwd(q, k, v, scale, bq, bk):
    o, lse = _blocked_fwd_impl(q, k, v, scale, bq, bk)
    return (o, lse), (q, k, v, o, lse)


def _blocked_bh_bwd(scale, bq, bk, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    return _blocked_bwd_impl(q, k, v, o, lse, do, dlse, scale, bq, bk)


blocked_bh_with_lse.defvjp(_blocked_bh_fwd, _blocked_bh_bwd)


def _blocked_bh(q, k, v, scale, bq, bk):
    return blocked_bh_with_lse(q, k, v, scale, bq, bk)[0]


def blocked_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                            block_q: int = DEFAULT_BLOCK_Q,
                            block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Streaming flash attention; (B, N, H, Dh) -> (B, N, H, Dh),
    differentiable, VMEM use independent of N."""
    from vitax.ops.attention import _from_bh, _to_bh

    n, dh = q.shape[1], q.shape[3]
    scale = dh ** -0.5
    bq = min(block_q, _pad_len(n, 128))
    bk = min(block_k, _pad_len(n, 128))
    o = _blocked_bh(_to_bh(q), _to_bh(k), _to_bh(v), scale, bq, bk)
    return _from_bh(o, q.shape)


# ---------------------------------------------------------------------------
# streaming attention with in-kernel dropout (round 5)
# ---------------------------------------------------------------------------
# The whole-N dropout kernels cap at MAX_SEQ_IN_VMEM; past it this variant
# keeps --att_dropout on the fused path too. The keep-mask is the SAME
# counter-hash as vitax/ops/attention.py, evaluated at each tile's GLOBAL
# (q, k) coordinates — the fwd's kv-streaming tiles and both backward
# kernels' differently-shaped tiles all regenerate identical decisions, so
# no mask residual exists anywhere. Dense semantics: mask the numerator
# terms, keep l/lse unmasked, divide by (1 - rate) at the end.


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def blocked_bh_dropout_lse(q, k, v, seedvec, scale, rate, bq, bk):
    """(BH, N, Dh) streaming attention with attention dropout, returning
    (o, lse); differentiable in both outputs (ring attention's merge).
    seedvec: (3,) uint32 [seed, q0, k0] (vitax.ops.attention._seedvec)."""
    return _blocked_fwd_impl(q, k, v, scale, bq, bk, seed=seedvec,
                             rate=rate)


def _blocked_drop_fwd(q, k, v, seedvec, scale, rate, bq, bk):
    o, lse = _blocked_fwd_impl(q, k, v, scale, bq, bk, seed=seedvec,
                               rate=rate)
    return (o, lse), (q, k, v, o, lse, seedvec)


def _blocked_drop_bwd(scale, rate, bq, bk, res, cts):
    import numpy as np
    q, k, v, o, lse, seedvec = res
    do, dlse = cts
    dq, dk, dv = _blocked_bwd_impl(
        q, k, v, o, lse, do, dlse, scale, bq, bk, seed=seedvec, rate=rate)
    return dq, dk, dv, np.zeros(seedvec.shape, jax.dtypes.float0)


blocked_bh_dropout_lse.defvjp(_blocked_drop_fwd, _blocked_drop_bwd)


def blocked_bh_dropout(q, k, v, seed, scale, rate, bq, bk):
    """(BH, N, Dh) streaming attention with attention dropout; seed is a
    traced uint32 scalar."""
    from vitax.ops.attention import _seedvec
    return blocked_bh_dropout_lse(q, k, v, _seedvec(seed), scale, rate,
                                  bq, bk)[0]


def blocked_dropout_attention(q, k, v, seed, rate: float,
                              block_q: int = DEFAULT_BLOCK_Q,
                              block_k: int = DEFAULT_BLOCK_K):
    """Streaming flash attention with in-kernel attention dropout;
    (B, N, H, Dh) -> (B, N, H, Dh), differentiable in q/k/v."""
    from vitax.ops.attention import _from_bh, _to_bh

    n, dh = q.shape[1], q.shape[3]
    scale = dh ** -0.5
    bq = min(block_q, _pad_len(n, 128))
    bk = min(block_k, _pad_len(n, 128))
    o = blocked_bh_dropout(_to_bh(q), _to_bh(k), _to_bh(v), seed, scale,
                           rate, bq, bk)
    return _from_bh(o, q.shape)
