"""Configuration: CLI flags and typed config.

Keeps the reference's exact 26-flag surface (names, defaults, and the ``--no_X`` /
store_false idiom) as a compatibility contract (reference run_vit_training.py:327-363),
plus vitax-specific extensions that default to reference-equivalent behavior.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class Config:
    """Typed training configuration.

    The first group mirrors the reference CLI one-to-one
    (reference run_vit_training.py:329-361). The ``vitax:`` group adds
    TPU-native knobs (mesh shape, dtype, kernels) with conservative defaults.
    """

    # --- data / io (reference :329-337) ---
    data_dir: str = "/datasets/imagenet-1k"
    fake_data: bool = False
    num_workers: int = 4
    prefetch_batches: int = 2           # host-prefetch depth of ShardedLoader (queued decoded batches)
    data_format: str = "imagefolder"    # imagefolder = per-file directory scan (reference parity);
    #   stream = .vtxshard streaming containers (vitax/data/stream/ — pack
    #   with tools/make_shards.py, point --data_dir at the shard root)
    stream_prefetch: int = 2            # host-prefetch depth of the streaming loader (>= 1)
    ckpt_dir: str = "/tmp/vit_fsdp"
    resume_epoch: int = 0               # N = resume from epoch N; -1 = auto-resume latest checkpoint
    ckpt_epoch_interval: int = 10
    zero_stall_ckpt: bool = False       # route saves through the zero-stall snapshot pipeline
    #   (vitax/checkpoint/snapshot.py): device->host staging is the only
    #   part on the loop thread; serialization + the Orbax write run on a
    #   background worker, so step N+1 dispatches immediately (ckpt_stall_s
    #   telemetry pins the stall ~0). The step program is bit-identical
    #   with this flag on or off.
    replicate_steps: int = 0            # >0: every N steps, mirror this host's staged state shard
    #   (checksummed, versioned) to its ring-buddy host over the
    #   coordination-service KV — after a lost host, elastic resume
    #   restores from the surviving buddy with ZERO shared-storage reads
    #   (vitax/checkpoint/peer.py). 0 = replication off.
    peer_dir: str = ""                  # local peer-store root (default <ckpt_dir>/peerstore;
    #   VITAX_PEER_DIR env overrides — point it at per-host scratch in
    #   production, NOT shared storage)
    keep_checkpoints: int = 0           # >0: checkpoint GC — prune committed epoch dirs beyond the
    #   newest K after each successful save (torn dirs never touched);
    #   0 = keep all (default)
    test_epoch_interval: int = 10
    log_step_interval: int = 20

    # --- model shape (reference :339-348; defaults = the 10.078B ViT) ---
    image_size: int = 224
    patch_size: int = 14
    embed_dim: int = 5120
    num_heads: int = 32
    num_blocks: int = 32
    mlp_ratio: float = 4.0
    pos_dropout: float = 0.0
    # NOTE: att_dropout > 0 stays on the fused kernels — every attention path
    # (whole-N, streamed, ring/ulysses sp, and their pipeline bodies at tp=1)
    # carries an in-kernel counter-hash dropout variant (vitax/ops/attention.py
    # dropout_keep_mask). The one remaining dense O(N^2) surface is the
    # pipeline body under tp > 1 (vitax/parallel/pipeline.py asserts on it).
    att_dropout: float = 0.0
    mlp_dropout: float = 0.0
    num_classes: int = 1000

    # --- optimization (reference :351-356) ---
    batch_size: int = 1024
    num_epochs: int = 300
    lr: float = 1e-3
    weight_decay: float = 0.1
    clip_grad_norm: float = 1.0
    warmup_steps: int = 10000

    # --- parallelism toggles (reference :357-361) ---
    grad_ckpt: bool = True              # --no_grad_ckpt clears
    reshard_after_forward: bool = True  # --no_reshard_after_forward clears (ZeRO-3 -> ZeRO-2)
    flatten_parameters: bool = False    # accepted for parity; a no-op under GSPMD (see parallel/sharding.py)
    run_without_fsdp: bool = False      # pure data-parallel baseline (params replicated)
    shard_on_cpu: bool = False          # host-side init + per-shard device_put (10B+ init w/o HBM OOM)

    # --- vitax: TPU-native extensions (all default to reference-equivalent behavior) ---
    seed: int = 0
    grad_accum_steps: int = 1           # K > 1: lax.scan over K microbatches of B/K inside the
    #   jitted step — one clip + AdamW update per loader batch, fp32 grad
    #   accumulators, peak activations ~ one microbatch (vitax/train/step.py)
    dtype: str = "bfloat16"             # compute dtype; params/opt state stay float32
    # Communication precision (vitax/parallel/sharding.py cast_to_compute):
    #   param_gather_dtype: dtype the FSDP collectives move for params. None
    #   resolves to --dtype, so the default bf16 run gathers bf16 (half the
    #   collective bytes) while --dtype float32 runs are untouched. Casting the
    #   *shards* before the gather commutes with the gather, so the forward is
    #   bitwise-identical to gather-then-cast; master params stay f32.
    #   grad_reduce_dtype: dtype the grad reduce-scatter / all-reduce moves.
    #   float32 (default) upcasts each device's bf16 partial before the
    #   reduction — exactly the current numerics; bfloat16 pins the reduction
    #   on bf16 bits for another 2x on grad comm (opt-in precision trade).
    param_gather_dtype: Optional[str] = None  # None -> follow --dtype
    grad_reduce_dtype: str = "float32"
    # Gather/compute overlap (vitax/models/vit.py make_overlap_forward):
    #   an explicit double-buffered gather schedule for the ZeRO-3 block scan.
    #   The scan carry holds the already-gathered params for block k while the
    #   body issues the all-gather (over "fsdp") for block k+1, so the
    #   collective overlaps block k's matmuls instead of serializing in front
    #   of them (XLA's latency-hiding scheduler cannot hoist a gather across a
    #   lax.scan iteration boundary). auto = enable when ZeRO-3 + scanned
    #   blocks + per-block remat (none_saveable) are active; off = the exact
    #   pre-overlap program; on = require it (validate() rejects configs the
    #   schedule cannot serve: pp, ZeRO-2/DP, unscanned blocks, no-remat).
    gather_overlap: str = "auto"        # auto | off | on
    use_flash_attention: bool = True    # Pallas flash-attention kernel on TPU (jnp fallback elsewhere)
    # Fused clip+AdamW optimizer (vitax/ops/fused_optimizer.py): one Pallas
    #   pass over the sharded state instead of the optax tree-of-ops. auto =
    #   on exactly when the kernels lower to real Mosaic (TPU backend, or
    #   VITAX_FORCE_MOSAIC=1 AOT compiles); on = force it anywhere (Pallas
    #   interpret mode off-TPU — the CI equivalence arms); off = the exact
    #   optax chain.
    fused_optimizer: str = "auto"       # auto | off | on
    # Mesh: (dp, fsdp, tp, sp). -1 on fsdp means "all remaining devices".
    dp_size: int = 1
    fsdp_size: int = -1
    tp_size: int = 1
    sp_size: int = 1
    sp_impl: str = "ring"               # ring (ppermute K/V rotation) | ulysses (all-to-all head<->token)
    pp_size: int = 1                    # pipeline stages (GPipe over the stacked layer axis; composes with dp and fsdp)
    pp_microbatches: int = 0            # GPipe microbatches per step (0 = pp_size; bubble = (S-1)/(M+S-1))
    pp_schedule: str = "gpipe"          # gpipe (autodiff backward, O(M) live acts) | 1f1b (interleaved
                                        #   fwd/bwd, O(S) live acts — enables large M)
    ep_size: int = 1                    # expert-parallel axis (also carries batch; experts sharded across it)
    moe_experts: int = 0                # 0 = dense reference MLP; >0 = top-1 MoE in every block
    moe_capacity_factor: float = 1.25   # static expert capacity C = ceil(cf * tokens / experts)
    moe_top_k: int = 1                  # 1 = Switch (top-1); 2 = GShard-style top-2 with renormalized gates
    moe_aux_weight: float = 0.01        # load-balance aux loss weight (Switch Transformer)
    moe_impl: str = "einsum"            # einsum (GShard one-hot — measured fastest on v5e) | gather
                                        #   (slot-index scatter + gathers; measured -23%, kept as the A/B arm)
    scan_blocks: bool = True            # lax.scan over stacked block params (one compile for L blocks)
    scan_unroll: int = 1                # blocks per scan step: >1 frees XLA to fuse across blocks
    #   (the scan's per-block dus-stacking constrains wgrad fusion layouts —
    #   measured l14/v5e: full unroll +29% step throughput; partial unroll
    #   keeps the stacked param tree and O(L/unroll) compile)
    remat_window: int = 0               # >1: remat around GROUPS of this many blocks (functional scan;
    #   saved residuals dus-stack once per group instead of per block — the
    #   wgrad-fusion experiment for the measured 85-100 TF/s stacking ceiling)
    device_normalize: bool = True       # ship uint8 batches; normalize on-device (4x less host->device traffic)
    # none_saveable = the reference's checkpoint_module semantics (recompute
    # everything) and the least HBM — the right default for the 10B+ flagship.
    # Measured on v5e l14 (BASELINE_MEASURED.json): dots_attn_saveable 192.9 >
    # dots_saveable 190.2 > none_saveable ~183 img/s/chip — bench selects
    # dots_attn_saveable where activations fit.
    remat_policy: str = "none_saveable" # none_saveable | dots_saveable | dots_attn_saveable (only if grad_ckpt)
    profile_dir: str = ""               # if set, capture a jax.profiler trace of a few steps
    profile_start_step: int = 2         # global step the profiler window opens after (with --profile_dir)
    profile_num_steps: int = 5          # steps the profiler window spans (historical default: steps 3-7)
    # --- vitax: telemetry (vitax/telemetry/; all host-side — the compiled
    # step program is identical with telemetry on or off) ---
    metrics_dir: str = ""               # if set, write one JSONL record per log step (schema 1:
    #   loss, lr, sec/iter, images/s, tokens/s, data-wait, MFU, HBM) under
    #   <metrics_dir>/metrics.jsonl; summarize with tools/metrics_report.py
    tensorboard: bool = False           # mirror step records as TB scalars under <metrics_dir>/tb
    #   (no-op with a warning when the tensorboard package is absent)
    peak_tflops: float = 0.0            # per-chip peak TFLOP/s for MFU; 0 = detect from the device
    #   kind (vitax/telemetry/flops.py PEAK_TFLOPS table)
    hang_timeout_s: float = 0.0         # >0: heartbeat watchdog — dump all-thread stacks + device
    #   memory (rank-tagged, job left running) after this many seconds
    #   without a completed step (vitax/telemetry/watchdog.py)
    hang_action: str = "dump"           # dump = stacks only, job left running (PR 4 behavior);
    #   checkpoint_exit = after the dump, emergency-save a committed mid-epoch
    #   checkpoint at the next step boundary and exit with code 42 so a
    #   supervisor (tools/supervise.py) restarts the run; a loop that never
    #   reaches a boundary is hard-exited with the same code after a deadline
    fault_plan: str = ""                # JSON fault-injection plan (vitax/faults.py; or the
    #   VITAX_FAULT_PLAN env var): deterministic crash/hang/write-error/
    #   loader-stall/SIGTERM drills at a chosen step or call site. "" (and
    #   no env var) = every hook is a zero-cost no-op; the compiled step
    #   program is identical either way (all hooks are host-side)
    control_sync_steps: int = 10        # multi-host control-word agreement cadence, in steps
    #   (vitax/train/control.py): SIGTERM/escalation/fault signals agreed
    #   across hosts every N steps (plus every epoch boundary) via one tiny
    #   collective. Hosts must use the same value. Single-host: signals are
    #   checked every step for free and this cadence is moot
    peer_heartbeat_s: float = 0.0       # >0: multi-host peer-liveness heartbeats through the
    #   coordination-service KV store every N seconds; a peer whose beat
    #   stops for peer_grace_s is declared dead and the survivors escalate
    #   to checkpoint_exit (exit 42) instead of blocking in ICI collectives
    #   forever. 0 = liveness off (single-host runs don't need it)
    peer_grace_s: float = 0.0           # silence window before a peer is declared lost, and the
    #   deadline for the survivor's own exit after the verdict; 0 = default
    #   (10 x peer_heartbeat_s)
    arbiter_url: str = ""               # chip-arbiter URL (python -m vitax.arbiter): rank 0 posts
    #   step/progress heartbeats there so borrow policy can gate on
    #   "training is actually progressing". Host-side reporter thread only
    #   (vitax/train/control.py ArbiterReporter) — the compiled step
    #   program is identical with or without it. "" = off
    compile_cache_dir: str = ""         # persistent XLA compile cache (restarts skip recompiles)
    debug_nans: bool = False            # opt-in jax_debug_nans (SURVEY.md section 5, race-detection analog)
    log_memory: bool = True             # include HBM stats in step log
    steps_per_epoch: int = 0            # override (0 = derive from dataset length // batch_size)
    max_steps: int = 0                  # hard stop after N optimizer steps (0 = no limit; for smoke/bench)
    eval_max_batches: int = 0           # cap val batches per eval (0 = full split, reference behavior)
    # --- vitax: serving (vitax/serve/ — the inference half of the stack) ---
    serve_port: int = 8000              # HTTP port for python -m vitax.serve (0 = ephemeral, tests)
    serve_max_batch: int = 8            # largest micro-batch bucket (power of two); the engine
    #   AOT-compiles every power-of-two bucket 1..serve_max_batch at startup
    #   so steady-state traffic never recompiles (vitax/serve/engine.py)
    max_batch_wait_ms: float = 5.0      # dynamic batcher flush deadline: a queued request waits at
    #   most this long for the bucket to fill (vitax/serve/batcher.py)
    serve_topk: int = 5                 # classes returned per /predict response
    serve_quant_dtype: str = ""         # expected weight quantization of the serve export: "" (full
    #   precision), "int8" or "float8_e4m3" (per-channel weights from
    #   consolidate.py --dtype, dequantized at use inside the jitted
    #   forward — vitax/serve/quant.py). The npz manifest is authoritative;
    #   this flag asserts it, and gates the VTX-R007 invariant arm
    serve_act_quant: str = "off"        # dynamic activation quantization for the serve forward:
    #   "off" or "int8" — per-tensor absmax activation scales computed
    #   inside the jitted forward so eligible matmuls (QKV/proj/MLP in
    #   blocks) run int8 x int8 with a float rescale. Requires
    #   --serve_quant_dtype int8 (int8 weights are the other operand) and
    #   a dense model (MoE dispatch stays float). Gated by the same
    #   quant_gate accuracy event as weight-only int8
    fused_dequant: str = "auto"         # Pallas fused dequant-matmul (vitax/ops/dequant_matmul.py):
    #   fuse weight dequant (+ activation quant when enabled) into the
    #   serve matmul so no dequantized weight block round-trips through
    #   HBM. "auto" = on when serving quantized weights on TPU (dense
    #   model), "on" forces it (interpret mode off-TPU), "off" keeps the
    #   jnp dot path. Pinned by the VTX-R009 invariant
    serve_queue_max: int = 1024         # dynamic batcher queue bound: submit() on a full queue raises
    #   QueueFull, which the single-engine server answers 503 (reason
    #   "queue_full") and the fleet router maps to an admission shed (429)
    #   — the backpressure floor under overload. 0 = unbounded (pre-PR-8)
    serve_request_timeout_s: float = 60.0  # ceiling a /predict handler waits on its batch future before
    #   answering 503: batcher deadline + one engine batch + generous slack
    #   (was the hardcoded REQUEST_TIMEOUT_S); surfaced in /metrics
    serve_brownout_enter_frac: float = 0.75  # brownout trigger: queue depth sustained at or above this
    #   fraction of --serve_queue_max for --serve_brownout_dwell_s enters
    #   degraded mode — topk clamped to 1, batcher deadline shortened to
    #   --serve_brownout_wait_ms, `degraded: true` advertised in /healthz
    #   and /metrics (vitax/serve/server.py BrownoutController). 0 = off
    serve_brownout_exit_frac: float = 0.25  # hysteretic recovery: depth sustained at or below this
    #   fraction for the same dwell exits degraded mode (must be <= the
    #   enter fraction so the two thresholds cannot chatter)
    serve_brownout_dwell_s: float = 2.0 # sustained-pressure window for BOTH brownout transitions:
    #   blips shorter than this never flip the mode
    serve_brownout_wait_ms: float = 1.0 # degraded-mode batcher flush deadline (replaces
    #   --max_batch_wait_ms while browned out; restored on recovery)
    serve_allow_chaos: bool = False     # arm POST /chaos: accepts a fault plan JSON body and
    #   installs it live (vitax/faults.py serve sites) so drills can inject
    #   into running replicas (tools/serve_bench.py --chaos). NEVER enable
    #   on a production replica — the endpoint is deliberately off unless
    #   this flag opts in
    serve_cache_max: int = 0            # router-side content-addressed prediction cache: entries
    #   kept (0 = off). Keyed by SHA-256 of the request bytes + topk;
    #   exact, because AOT-pinned classification is deterministic — a hit
    #   returns the stored bytes verbatim without touching a replica
    serve_cache_ttl_s: float = 300.0    # prediction-cache entry lifetime; expired entries re-dispatch
    #   (bounds staleness across model redeploys that keep the router up)
    serve_batch_window_ms: float = 0.0  # cross-replica continuous batching (fleet router): hold the
    #   first concurrent /predict up to this long to compose a group,
    #   dispatched as ONE /predict_batch to one replica (0 = off).
    #   Counters the least-loaded router spreading co-arrivals so thin
    #   that every replica batcher flushes at batch_size 1
    serve_batch_max: int = 0            # composed-group size cap (0 = use --serve_max_batch, the
    #   largest engine bucket — bigger groups would split anyway)

    # --- scenario registry (vitax/programs/) ---
    task: str = "train"                 # which registered scenario this run executes (train /
    #   finetune / probe / distill); each scenario's validator runs at the
    #   end of validate() (vitax/programs/registry.py)
    init_npz: str = ""                  # finetune warm start: consolidated npz export whose params
    #   overwrite the fresh init leaf-for-leaf (head may re-init)
    teacher_npz: str = ""               # distillation teacher: consolidated npz export served as the
    #   frozen eval-mode tower inside the distill step
    reinit_head: bool = False           # finetune: keep the fresh head init even when the export's
    #   head shapes match (training a new label space of the same size)
    backbone_lr_mult: float = 1.0       # finetune: multiply non-head updates by this after AdamW
    #   (1.0 = off; 0 freezes the backbone — but prefer --task probe, which
    #   also drops the backbone optimizer moments)
    distill_alpha: float = 0.5          # distill loss mix: (1-alpha)*CE(labels) + alpha*KL(teacher)
    distill_temp: float = 2.0           # distill softmax temperature T (KL term scaled by T^2)

    @property
    def resolved_param_gather_dtype(self) -> str:
        """Gather-dtype policy after None -> --dtype resolution."""
        return self.param_gather_dtype or self.dtype

    @property
    def comm_cast_active(self) -> bool:
        """True when params should be downcast (sharded) before FSDP gathers."""
        return self.dtype == "bfloat16" and self.resolved_param_gather_dtype == "bfloat16"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def mlp_hidden_dim(self) -> int:
        return int(self.embed_dim * self.mlp_ratio)

    def validate(self) -> "Config":
        assert self.image_size % self.patch_size == 0, (
            f"image_size {self.image_size} not divisible by patch_size {self.patch_size}")
        assert self.embed_dim % self.num_heads == 0, (
            f"embed_dim {self.embed_dim} not divisible by num_heads {self.num_heads}")
        assert self.sp_impl in ("ring", "ulysses"), (
            f"unknown sp_impl {self.sp_impl!r} (expected 'ring' or 'ulysses')")
        for name in ("pos_dropout", "att_dropout", "mlp_dropout"):
            rate = getattr(self, name)
            assert 0.0 <= rate < 1.0, (
                f"--{name} must be in [0, 1), got {rate}: rate >= 1 would "
                f"zero every activation and the kernels' 1/(1-rate) rescale "
                f"turns that into inf/NaN rather than torch's all-zeros")
        assert self.prefetch_batches >= 1, (
            f"--prefetch_batches must be >= 1, got {self.prefetch_batches}: "
            f"the loader needs at least one queued batch to hand the consumer")
        assert self.data_format in ("imagefolder", "stream"), (
            f"unknown data_format {self.data_format!r} "
            f"(expected 'imagefolder' or 'stream')")
        assert self.stream_prefetch >= 1, (
            f"--stream_prefetch must be >= 1, got {self.stream_prefetch}: "
            f"the streaming loader needs at least one queued batch to hand "
            f"the consumer")
        if self.data_format == "stream":
            assert not self.fake_data, (
                "--data_format stream with --fake_data is contradictory: "
                "fake data needs no input pipeline — generate a shard set "
                "from an ImageFolder tree with tools/make_shards.py instead")
            assert self.data_dir, (
                "--data_format stream needs --data_dir pointing at a shard "
                "root (the output of tools/make_shards.py, holding "
                "train/stream_meta.json)")
        assert self.grad_accum_steps >= 1, (
            f"--grad_accum_steps must be >= 1, got {self.grad_accum_steps}")
        assert self.gather_overlap in ("auto", "off", "on"), (
            f"unknown gather_overlap {self.gather_overlap!r} "
            f"(expected 'auto', 'off' or 'on')")
        assert self.fused_optimizer in ("auto", "off", "on"), (
            f"unknown fused_optimizer {self.fused_optimizer!r} "
            f"(expected 'auto', 'off' or 'on')")
        if self.gather_overlap == "on":
            assert self.pp_size == 1, (
                "--gather_overlap on with --pp_size > 1 is rejected: the "
                "pipeline schedules own their gathers (just-in-time in-body "
                "gathers pinned per stage, vitax/parallel/pipeline.py) and a "
                "second prefetch schedule would double-gather every block")
            assert self.scan_blocks, (
                "--gather_overlap on needs the scanned stacked block tree "
                "(drop --no_scan_blocks): the double-buffered prefetch slot "
                "rides the scan carry")
            assert self.reshard_after_forward and not self.run_without_fsdp, (
                "--gather_overlap on needs ZeRO-3 (per-block gathers): under "
                "ZeRO-2 (--no_reshard_after_forward) the whole tree is "
                "gathered once at the step top and under --run_without_fsdp "
                "params are replicated — there is no per-block gather to "
                "overlap")
            assert self.grad_ckpt and self.remat_policy == "none_saveable", (
                "--gather_overlap on requires --grad_ckpt with "
                "remat_policy=none_saveable: the schedule's backward "
                "re-gathers each block's shards and recomputes its forward "
                "(exactly per-block remat); other policies save residuals "
                "the overlap path would silently discard")
        if self.grad_accum_steps > 1:
            assert self.batch_size % self.grad_accum_steps == 0, (
                f"--batch_size {self.batch_size} not divisible by "
                f"--grad_accum_steps {self.grad_accum_steps}: the global "
                f"batch is reshaped to (K, B/K, ...) inside the step")
            assert self.pp_size == 1, (
                "--grad_accum_steps > 1 with --pp_size > 1 is rejected: the "
                "pipeline already microbatches the step (--pp_microbatches) "
                "and nesting a second accumulation scan around it would "
                "double-count the memory/bubble trade — raise "
                "--pp_microbatches instead")
        assert self.scan_unroll >= 1, (
            f"--scan_unroll must be >= 1, got {self.scan_unroll}")
        if self.remat_window > 1:
            assert self.scan_blocks and self.grad_ckpt, (
                "--remat_window needs the scanned stacked tree and remat on")
            assert self.num_blocks % self.remat_window == 0, (
                f"--num_blocks {self.num_blocks} not divisible by "
                f"--remat_window {self.remat_window}")
            assert self.scan_unroll == 1, (
                "--remat_window subsumes --scan_unroll (the window IS the "
                "unrolled group); drop one of the two")
            assert self.pp_size == 1, (
                "--remat_window composes with dropout and MoE (v2) but not "
                "pp: the pipeline path owns checkpoint placement "
                "(vitax/parallel/pipeline.py)")
        if self.pp_size > 1:
            assert self.scan_blocks, "--pp_size needs the stacked block tree (drop --no_scan_blocks)"
            assert self.reshard_after_forward or self.fsdp_size == 1, (
                "--no_reshard_after_forward (ZeRO-2) under --pp_size > 1 "
                "with fsdp sharding is not supported: the pipeline body "
                "gathers each block's shards just-in-time (ZeRO-3 "
                "semantics) and a step-top full gather would defeat that. "
                "With --fsdp_size 1 the flag is a no-op and allowed; "
                "--fsdp_size -1 is treated as sharded here (validate() runs "
                "before the device count is known) — pass an explicit "
                "--fsdp_size 1 if the remaining mesh is a single device")
            assert self.num_blocks % self.pp_size == 0, (
                f"--num_blocks {self.num_blocks} not divisible by --pp_size {self.pp_size}")
            assert self.pp_microbatches >= 0
            assert self.pp_schedule in ("gpipe", "1f1b"), self.pp_schedule
            if self.moe_experts > 0:
                assert self.ep_size == 1 or self.moe_impl == "einsum", (
                    "--moe_experts with --ep_size > 1 under --pp_size > 1 "
                    "runs the manual all-to-all dispatch inside the pipeline "
                    "body, which only the einsum impl implements "
                    "(vitax/models/moe.py MoeMlp.ep_axis)")
                assert self.tp_size == 1 and self.sp_size == 1, (
                    "--moe_experts under --pp_size > 1 composes with "
                    "dp/fsdp/ep only: the MoE dispatch einsums inside the "
                    "pipeline body are not exercised under auto-tp/sp meshes")
            if self.pp_schedule == "1f1b":
                assert max(self.pos_dropout, self.att_dropout,
                           self.mlp_dropout) == 0.0 and self.moe_experts == 0, (
                    "--pp_schedule 1f1b v1 is dense/deterministic only "
                    "(dropout and MoE ride the gpipe schedule); the "
                    "interleaved backward always recomputes the stage "
                    "forward (none_saveable semantics)")
                assert self.tp_size == 1 and self.sp_size == 1, (
                    "--pp_schedule 1f1b runs a fully-manual shard_map "
                    "engine; tp/sp under pp ride the gpipe schedule "
                    "(GSPMD-auto axes in the pipeline body)")
        if self.ep_size > 1:
            assert self.moe_experts > 0, "--ep_size > 1 needs --moe_experts"
            assert self.moe_experts % self.ep_size == 0, (
                f"--moe_experts {self.moe_experts} not divisible by "
                f"--ep_size {self.ep_size}")
        if self.moe_experts > 0:
            assert self.moe_impl in ("gather", "einsum"), (
                f"unknown moe_impl {self.moe_impl!r}")
            assert self.moe_top_k in (1, 2), self.moe_top_k
            assert self.moe_top_k <= self.moe_experts, (
                f"--moe_top_k {self.moe_top_k} > --moe_experts "
                f"{self.moe_experts}: the second choice would be a dead "
                f"branch with gate ~0")
        assert self.profile_start_step >= 0, (
            f"--profile_start_step must be >= 0, got {self.profile_start_step}")
        assert self.profile_num_steps >= 1, (
            f"--profile_num_steps must be >= 1, got {self.profile_num_steps}: "
            f"an empty profiler window would open a trace it never closes "
            f"in-loop")
        assert self.peak_tflops >= 0, (
            f"--peak_tflops must be >= 0 (0 = detect from device kind), "
            f"got {self.peak_tflops}")
        assert self.hang_timeout_s >= 0, (
            f"--hang_timeout_s must be >= 0 (0 = watchdog off), "
            f"got {self.hang_timeout_s}")
        assert self.hang_action in ("dump", "checkpoint_exit"), (
            f"unknown hang_action {self.hang_action!r} (expected 'dump' or "
            f"'checkpoint_exit')")
        if self.fault_plan:
            from vitax import faults
            try:  # fail at startup, not at the step the plan names
                faults.parse_plan(self.fault_plan)
            except ValueError as e:
                raise AssertionError(f"--fault_plan invalid: {e}") from e
        assert self.control_sync_steps >= 1, (
            f"--control_sync_steps must be >= 1 (it is a collective cadence "
            f"every host shares), got {self.control_sync_steps}")
        assert self.peer_heartbeat_s >= 0, (
            f"--peer_heartbeat_s must be >= 0 (0 = liveness off), "
            f"got {self.peer_heartbeat_s}")
        assert self.peer_grace_s >= 0, (
            f"--peer_grace_s must be >= 0 (0 = 10 x peer_heartbeat_s), "
            f"got {self.peer_grace_s}")
        assert not (self.peer_grace_s > 0 and self.peer_heartbeat_s == 0), (
            "--peer_grace_s without --peer_heartbeat_s does nothing: the "
            "grace window bounds heartbeat silence, and no heartbeats are "
            "being sent")
        assert self.replicate_steps >= 0, (
            f"--replicate_steps must be >= 0 (0 = peer replication off), "
            f"got {self.replicate_steps}")
        assert self.keep_checkpoints >= 0, (
            f"--keep_checkpoints must be >= 0 (0 = keep all), "
            f"got {self.keep_checkpoints}")
        assert not (self.peer_dir and self.replicate_steps == 0), (
            "--peer_dir without --replicate_steps does nothing: the peer "
            "store is only written by the replication window")
        if self.tensorboard:
            assert self.metrics_dir, (
                "--tensorboard needs --metrics_dir: the TB event files live "
                "under <metrics_dir>/tb next to the JSONL record they mirror")
        assert self.eval_max_batches >= 0, (
            f"--eval_max_batches must be >= 0 (0 = evaluate the full val "
            f"split), got {self.eval_max_batches}: a negative cap would "
            f"silently skip evaluation entirely")
        assert 0 <= self.serve_port <= 65535, (
            f"--serve_port must be in [0, 65535] (0 = ephemeral port, for "
            f"tests), got {self.serve_port}")
        assert self.serve_max_batch >= 1 and (
            self.serve_max_batch & (self.serve_max_batch - 1)) == 0, (
            f"--serve_max_batch must be a power of two >= 1, got "
            f"{self.serve_max_batch}: the engine pads requests to "
            f"power-of-two buckets (1, 2, 4, ...) and AOT-compiles each one "
            f"at startup — a non-power-of-two cap would leave its own "
            f"bucket uncompiled")
        assert self.max_batch_wait_ms >= 0, (
            f"--max_batch_wait_ms must be >= 0 (0 = flush every request "
            f"immediately), got {self.max_batch_wait_ms}")
        assert self.serve_quant_dtype in ("", "int8", "float8_e4m3"), (
            f"--serve_quant_dtype must be '', 'int8' or 'float8_e4m3', got "
            f"{self.serve_quant_dtype!r}: these are the dtypes the __quant__ "
            f"manifest schema implements (vitax/checkpoint/consolidate.py "
            f"QUANT_DTYPES)")
        assert self.serve_act_quant in ("off", "int8"), (
            f"--serve_act_quant must be 'off' or 'int8', got "
            f"{self.serve_act_quant!r}: int8 is the only activation "
            f"quantization implemented (per-tensor dynamic absmax)")
        if self.serve_act_quant != "off":
            assert self.serve_quant_dtype == "int8", (
                f"--serve_act_quant {self.serve_act_quant} requires "
                f"--serve_quant_dtype int8 (int8 x int8 matmuls need int8 "
                f"weights as the other operand), got serve_quant_dtype="
                f"{self.serve_quant_dtype!r}")
            assert self.moe_experts == 0, (
                f"--serve_act_quant is dense-model only (MoE expert dispatch "
                f"keeps its float einsum path), got --moe_experts "
                f"{self.moe_experts}")
        assert self.fused_dequant in ("auto", "on", "off"), (
            f"--fused_dequant must be 'auto', 'on' or 'off', got "
            f"{self.fused_dequant!r}")
        if self.fused_dequant == "on":
            assert self.serve_quant_dtype, (
                f"--fused_dequant on requires a quantized "
                f"--serve_quant_dtype: there is no weight dequant to fuse "
                f"into a full-precision serve matmul")
            assert self.moe_experts == 0, (
                f"--fused_dequant on is dense-model only (MoE expert "
                f"matmuls keep their einsum path), got --moe_experts "
                f"{self.moe_experts}")
        assert self.serve_topk >= 1, (
            f"--serve_topk must be >= 1, got {self.serve_topk}; values above "
            f"num_classes are clamped by the engine at load time "
            f"(vitax/serve/engine.py)")
        assert self.serve_queue_max >= 0, (
            f"--serve_queue_max must be >= 0 (0 = unbounded), got "
            f"{self.serve_queue_max}: the batcher's pending deque is the "
            f"only queue in the serve path and a negative bound is "
            f"meaningless")
        assert self.serve_request_timeout_s > 0, (
            f"--serve_request_timeout_s must be > 0, got "
            f"{self.serve_request_timeout_s}: a /predict handler that waits "
            f"zero seconds on its batch future would answer 503 before the "
            f"batcher could possibly flush")
        assert 0.0 <= self.serve_brownout_enter_frac <= 1.0, (
            f"--serve_brownout_enter_frac must be in [0, 1] (a fraction of "
            f"--serve_queue_max; 0 = brownout off), got "
            f"{self.serve_brownout_enter_frac}")
        if self.serve_brownout_enter_frac > 0:
            assert (0.0 <= self.serve_brownout_exit_frac
                    <= self.serve_brownout_enter_frac), (
                f"--serve_brownout_exit_frac must be in [0, "
                f"enter_frac={self.serve_brownout_enter_frac}], got "
                f"{self.serve_brownout_exit_frac}: an exit threshold above "
                f"the enter threshold would make the hysteresis chatter")
        assert self.serve_brownout_dwell_s >= 0, (
            f"--serve_brownout_dwell_s must be >= 0, got "
            f"{self.serve_brownout_dwell_s}")
        assert self.serve_cache_max >= 0, (
            f"--serve_cache_max must be >= 0 (0 = prediction cache off), "
            f"got {self.serve_cache_max}")
        assert self.serve_cache_ttl_s > 0, (
            f"--serve_cache_ttl_s must be > 0, got {self.serve_cache_ttl_s}: "
            f"a cache that never expires would replay answers across model "
            f"redeploys; disable the cache with --serve_cache_max 0 instead")
        assert self.serve_batch_window_ms >= 0, (
            f"--serve_batch_window_ms must be >= 0 (0 = cross-replica "
            f"continuous batching off), got {self.serve_batch_window_ms}")
        assert self.serve_batch_max >= 0, (
            f"--serve_batch_max must be >= 0 (0 = use --serve_max_batch), "
            f"got {self.serve_batch_max}")
        assert self.serve_brownout_wait_ms >= 0, (
            f"--serve_brownout_wait_ms must be >= 0 (0 = flush every "
            f"request immediately while degraded), got "
            f"{self.serve_brownout_wait_ms}")
        assert self.resolved_param_gather_dtype in ("bfloat16", "float32"), (
            f"unknown param_gather_dtype {self.param_gather_dtype!r}")
        assert self.grad_reduce_dtype in ("bfloat16", "float32"), (
            f"unknown grad_reduce_dtype {self.grad_reduce_dtype!r}")
        if self.dtype == "float32":
            assert self.param_gather_dtype != "bfloat16", (
                "--param_gather_dtype bfloat16 with --dtype float32 would gather a "
                "downcast tree into an f32 model and silently change compute "
                "precision; use --dtype bfloat16 (f32 master params are kept "
                "either way)")
        if self.grad_reduce_dtype == "bfloat16":
            assert self.comm_cast_active, (
                "--grad_reduce_dtype bfloat16 requires the bf16 comm-cast to be "
                "active (--dtype bfloat16 and param_gather_dtype bfloat16): the "
                "bf16 reduction rides the cast boundary")
        assert 0.0 <= self.distill_alpha <= 1.0, (
            f"--distill_alpha must be in [0, 1] (the CE/KL mix), got "
            f"{self.distill_alpha}")
        assert self.distill_temp > 0, (
            f"--distill_temp must be > 0, got {self.distill_temp}")
        assert self.backbone_lr_mult >= 0, (
            f"--backbone_lr_mult must be >= 0, got {self.backbone_lr_mult}")
        # scenario dispatch: each --task's pairwise flag checks live with its
        # registry entry (vitax/programs/registry.py), not here — this
        # validator stops accreting per-workload blocks
        from vitax.programs.registry import get_scenario
        get_scenario(self.task).validate(self)
        return self


def build_parser() -> argparse.ArgumentParser:
    """Argparse surface: reference flags verbatim + `vitax:`-group extensions."""
    parser = argparse.ArgumentParser(description="vitax: TPU-native large-ViT FSDP training")

    # Reference flag surface (run_vit_training.py:329-361) — names and defaults are a contract.
    parser.add_argument("--data_dir", type=str, default="/datasets/imagenet-1k")
    parser.add_argument("--fake_data", action="store_true", dest="fake_data")
    parser.add_argument("--num_workers", type=int, default=4)
    parser.add_argument("--ckpt_dir", type=str, default="/tmp/vit_fsdp")
    parser.add_argument("--resume_epoch", type=int, default=0)
    parser.add_argument("--ckpt_epoch_interval", type=int, default=10)
    parser.add_argument("--zero_stall_ckpt", action="store_true",
                        dest="zero_stall_ckpt",
                        help="route checkpoint saves through the zero-stall "
                             "snapshot pipeline (vitax/checkpoint/"
                             "snapshot.py): staging on the loop thread, "
                             "serialize + Orbax write on a background "
                             "worker — step N+1 never waits for a "
                             "non-final save")
    parser.add_argument("--replicate_steps", type=int, default=0,
                        help=">0: every N steps, mirror this host's staged "
                             "state shard to its ring-buddy host over the "
                             "coordination-service KV (vitax/checkpoint/"
                             "peer.py) so a lost host restores from the "
                             "surviving buddy without shared storage "
                             "(0 = off)")
    parser.add_argument("--peer_dir", type=str, default="",
                        help="local peer-store root (default <ckpt_dir>/"
                             "peerstore; VITAX_PEER_DIR env overrides) — "
                             "per-host scratch, not shared storage")
    parser.add_argument("--keep_checkpoints", type=int, default=0,
                        help=">0: checkpoint GC — prune committed epoch "
                             "dirs beyond the newest K after each save; "
                             "torn dirs are never touched (0 = keep all)")
    parser.add_argument("--test_epoch_interval", type=int, default=10)
    parser.add_argument("--log_step_interval", type=int, default=20)

    parser.add_argument("--image_size", type=int, default=224)
    parser.add_argument("--patch_size", type=int, default=14)
    parser.add_argument("--embed_dim", type=int, default=5120)
    parser.add_argument("--num_heads", type=int, default=32)
    parser.add_argument("--num_blocks", type=int, default=32)
    parser.add_argument("--mlp_ratio", type=float, default=4.0)
    parser.add_argument("--pos_dropout", type=float, default=0.0)
    parser.add_argument("--att_dropout", type=float, default=0.0)
    parser.add_argument("--mlp_dropout", type=float, default=0.0)
    parser.add_argument("--num_classes", type=int, default=1000)

    parser.add_argument("--batch_size", type=int, default=1024)
    parser.add_argument("--num_epochs", type=int, default=300)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--weight_decay", type=float, default=0.1)
    parser.add_argument("--clip_grad_norm", type=float, default=1.0)
    parser.add_argument("--warmup_steps", type=int, default=10000)
    parser.add_argument("--no_grad_ckpt", action="store_false", dest="grad_ckpt")
    parser.add_argument("--no_reshard_after_forward", action="store_false", dest="reshard_after_forward")
    parser.add_argument("--flatten_parameters", action="store_true", dest="flatten_parameters")
    parser.add_argument("--run_without_fsdp", action="store_true", dest="run_without_fsdp")
    parser.add_argument("--shard_on_cpu", action="store_true", dest="shard_on_cpu")

    # vitax extensions
    ext = parser.add_argument_group("vitax")
    ext.add_argument("--seed", type=int, default=0)
    ext.add_argument("--prefetch_batches", type=int, default=2,
                     help="host-prefetch depth: decoded batches the loader "
                          "keeps queued ahead of the training loop (>= 1)")
    ext.add_argument("--data_format", type=str, default="imagefolder",
                     choices=["imagefolder", "stream"],
                     help="input pipeline: imagefolder = per-file directory "
                          "scan (reference parity); stream = .vtxshard "
                          "streaming containers (vitax/data/stream/) — pack "
                          "an ImageFolder tree with tools/make_shards.py "
                          "and point --data_dir at the shard root")
    ext.add_argument("--stream_prefetch", type=int, default=2,
                     help="host-prefetch depth of the streaming loader: "
                          "decoded batches kept queued ahead of the "
                          "training loop (>= 1; --data_format stream)")
    ext.add_argument("--gather_overlap", type=str, default="auto",
                     choices=["auto", "off", "on"],
                     help="double-buffered ZeRO-3 block-param gathers: the "
                          "scan body consumes the already-gathered params for "
                          "block k and issues the all-gather for block k+1, "
                          "overlapping the collective with block k's compute. "
                          "auto (default) = enable under zero3 + scanned "
                          "blocks + none_saveable remat; off = the exact "
                          "pre-overlap program; on = require it (rejected "
                          "under pp / ZeRO-2 / DP / --no_scan_blocks).")
    ext.add_argument("--fused_optimizer", type=str, default="auto",
                     choices=["auto", "off", "on"],
                     help="fused clip+AdamW Pallas kernel over the sharded "
                          "state (vitax/ops/fused_optimizer.py): one launch "
                          "per leaf group writing (param, mu, nu) in place. "
                          "auto (default) = on when the kernels lower to real "
                          "Mosaic (TPU / VITAX_FORCE_MOSAIC); on = force it "
                          "anywhere (interpret mode off-TPU); off = the "
                          "exact optax chain.")
    ext.add_argument("--grad_accum_steps", type=int, default=1)
    ext.add_argument("--dtype", type=str, default="bfloat16", choices=["bfloat16", "float32"])
    ext.add_argument("--param_gather_dtype", type=str, default=None,
                     choices=["bfloat16", "float32"],
                     help="dtype the FSDP param collectives (ZeRO-3 per-block "
                          "all-gathers, the ZeRO-2 step-top gather, pipeline "
                          "in-body gathers) move on the wire. Default: follow "
                          "--dtype, i.e. bf16 runs gather bf16 (2x fewer bytes, "
                          "bitwise-identical forward: casting shards commutes "
                          "with the gather); float32 forces the pre-PR f32 "
                          "gathers. Rejected with --dtype float32.")
    ext.add_argument("--grad_reduce_dtype", type=str, default="float32",
                     choices=["float32", "bfloat16"],
                     help="dtype the gradient reduce-scatter / all-reduce moves. "
                          "float32 (default) upcasts bf16 wgrad partials before "
                          "the cross-device reduction — exact current numerics; "
                          "bfloat16 reduces on bf16 bits for another 2x on grad "
                          "comm (~1e-2 step agreement; needs the bf16 gather "
                          "policy active).")
    ext.add_argument("--no_flash_attention", action="store_false", dest="use_flash_attention")
    ext.add_argument("--dp_size", type=int, default=1)
    ext.add_argument("--fsdp_size", type=int, default=-1)
    ext.add_argument("--tp_size", type=int, default=1)
    ext.add_argument("--sp_size", type=int, default=1)
    ext.add_argument("--sp_impl", type=str, default="ring",
                     choices=["ring", "ulysses"])
    ext.add_argument("--pp_size", type=int, default=1)
    ext.add_argument("--pp_microbatches", type=int, default=0)
    ext.add_argument("--pp_schedule", type=str, default="gpipe",
                     choices=["gpipe", "1f1b"])
    ext.add_argument("--ep_size", type=int, default=1)
    ext.add_argument("--moe_experts", type=int, default=0)
    ext.add_argument("--moe_capacity_factor", type=float, default=1.25)
    ext.add_argument("--moe_top_k", type=int, default=1, choices=[1, 2])
    ext.add_argument("--moe_aux_weight", type=float, default=0.01)
    ext.add_argument("--moe_impl", type=str, default="einsum",
                     choices=["gather", "einsum"])
    ext.add_argument("--no_scan_blocks", action="store_false", dest="scan_blocks")
    ext.add_argument("--scan_unroll", type=int, default=1)
    ext.add_argument("--remat_window", type=int, default=0)
    ext.add_argument("--host_normalize", action="store_false", dest="device_normalize")
    ext.add_argument("--remat_policy", type=str, default=Config.remat_policy,
                     choices=["none_saveable", "dots_saveable", "dots_attn_saveable"])
    ext.add_argument("--profile_dir", type=str, default="")
    ext.add_argument("--profile_start_step", type=int, default=2,
                     help="global step count after which the jax.profiler "
                          "trace window opens (with --profile_dir; default 2 "
                          "skips the compile step)")
    ext.add_argument("--profile_num_steps", type=int, default=5,
                     help="how many steps the profiler window spans "
                          "(default 5 = the historical steps-3..7 window)")
    ext.add_argument("--metrics_dir", type=str, default="",
                     help="write one JSONL telemetry record per log step "
                          "(schema 1: loss, lr, sec/iter, tokens/s, "
                          "data-wait, MFU, HBM) under "
                          "<metrics_dir>/metrics.jsonl; summarize with "
                          "tools/metrics_report.py")
    ext.add_argument("--tensorboard", action="store_true", dest="tensorboard",
                     help="mirror telemetry records as TensorBoard scalars "
                          "under <metrics_dir>/tb (warns and degrades to a "
                          "no-op when tensorboard is not installed)")
    ext.add_argument("--peak_tflops", type=float, default=0.0,
                     help="per-chip peak TFLOP/s for MFU accounting "
                          "(0 = detect from the device kind via the "
                          "vitax/telemetry/flops.py table)")
    ext.add_argument("--hang_timeout_s", type=float, default=0.0,
                     help=">0: watchdog dumps all-thread Python stacks + "
                          "device memory stats (rank-tagged, without killing "
                          "the job) after this many seconds with no "
                          "completed step")
    ext.add_argument("--hang_action", type=str, default="dump",
                     choices=["dump", "checkpoint_exit"],
                     help="what the watchdog does after its dump: dump = "
                          "leave the job running (default); checkpoint_exit "
                          "= emergency-save a committed checkpoint at the "
                          "next step boundary and exit 42 for a supervisor "
                          "(tools/supervise.py) to restart")
    ext.add_argument("--control_sync_steps", type=int, default=10,
                     help="multi-host failure-signal agreement cadence in "
                          "steps (vitax/train/control.py; one tiny "
                          "collective per cadence, plus every epoch "
                          "boundary) — hosts must share the same value")
    ext.add_argument("--peer_heartbeat_s", type=float, default=0.0,
                     help=">0: heartbeat peers through the coordination "
                          "service every N seconds; a peer silent for "
                          "--peer_grace_s is declared dead and survivors "
                          "escalate to checkpoint_exit (exit 42) instead "
                          "of blocking in collectives (0 = off)")
    ext.add_argument("--peer_grace_s", type=float, default=0.0,
                     help="heartbeat-silence window before a peer is "
                          "declared lost, and the survivor's own exit "
                          "deadline after the verdict (0 = 10 x "
                          "--peer_heartbeat_s)")
    ext.add_argument("--arbiter_url", type=str, default="",
                     help="chip-arbiter URL (python -m vitax.arbiter): "
                          "rank 0 posts step/progress heartbeats there so "
                          "the arbiter's borrow policy sees live training "
                          "telemetry (host-side thread; the compiled step "
                          "program is unchanged). \"\" = off")
    ext.add_argument("--fault_plan", type=str, default="",
                     help="JSON fault-injection plan (vitax/faults.py), e.g. "
                          "'{\"site\": \"step\", \"at\": 6, \"action\": "
                          "\"crash\"}' — deterministic crash/hang/"
                          "write-error/loader-stall/SIGTERM drills for the "
                          "failure-reaction machinery (VITAX_FAULT_PLAN env "
                          "var is the flagless equivalent)")
    ext.add_argument("--compile_cache_dir", type=str, default="")
    ext.add_argument("--debug_nans", action="store_true", dest="debug_nans")
    ext.add_argument("--no_log_memory", action="store_false", dest="log_memory")
    ext.add_argument("--steps_per_epoch", type=int, default=0)
    ext.add_argument("--max_steps", type=int, default=0)
    ext.add_argument("--eval_max_batches", type=int, default=0)
    serve = parser.add_argument_group("vitax serving (vitax/serve/)")
    serve.add_argument("--serve_port", type=int, default=8000,
                       help="HTTP port for python -m vitax.serve "
                            "(0 = ephemeral, for tests)")
    serve.add_argument("--serve_max_batch", type=int, default=8,
                       help="largest micro-batch bucket (power of two); "
                            "every power-of-two bucket up to it is "
                            "AOT-compiled at startup so steady-state "
                            "traffic never recompiles")
    serve.add_argument("--max_batch_wait_ms", type=float, default=5.0,
                       help="dynamic batcher deadline: a queued request "
                            "waits at most this long for the largest "
                            "bucket to fill before the batch is flushed")
    serve.add_argument("--serve_topk", type=int, default=5,
                       help="classes returned per /predict response")
    serve.add_argument("--serve_quant_dtype", type=str, default="",
                       choices=["", "int8", "float8_e4m3"],
                       help="expected weight quantization of the serve "
                            "export ('' = full precision); asserts the npz "
                            "__quant__ manifest matches at load")
    serve.add_argument("--serve_act_quant", type=str, default="off",
                       choices=["off", "int8"],
                       help="dynamic activation quantization for the serve "
                            "forward: int8 computes per-tensor absmax "
                            "activation scales inside the jitted forward so "
                            "eligible matmuls run int8 x int8 (requires "
                            "--serve_quant_dtype int8, dense model)")
    serve.add_argument("--fused_dequant", type=str, default="auto",
                       choices=["auto", "on", "off"],
                       help="Pallas fused dequant-matmul for quantized "
                            "serving: auto = on-TPU dense quantized serving "
                            "only; on forces it (interpret mode off-TPU); "
                            "off keeps the jnp dot path (VTX-R009 pins the "
                            "fused program)")
    serve.add_argument("--serve_queue_max", type=int, default=1024,
                       help="dynamic batcher queue bound: a submit against "
                            "a full queue raises QueueFull, answered 503 "
                            "(reason queue_full) by the single-engine "
                            "server and shed as 429 by the fleet router "
                            "(0 = unbounded)")
    serve.add_argument("--serve_request_timeout_s", type=float, default=60.0,
                       help="seconds a /predict handler waits on its batch "
                            "future before answering 503 (> 0; surfaced in "
                            "/metrics)")
    serve.add_argument("--serve_brownout_enter_frac", type=float,
                       default=0.75,
                       help="brownout trigger: queue depth sustained at or "
                            "above this fraction of --serve_queue_max for "
                            "--serve_brownout_dwell_s enters degraded mode "
                            "(topk clamped to 1, batcher deadline shortened, "
                            "degraded: true in /healthz; 0 = off)")
    serve.add_argument("--serve_brownout_exit_frac", type=float, default=0.25,
                       help="hysteretic brownout recovery: depth sustained "
                            "at or below this fraction for the dwell exits "
                            "degraded mode (must be <= the enter fraction)")
    serve.add_argument("--serve_brownout_dwell_s", type=float, default=2.0,
                       help="sustained-pressure window for both brownout "
                            "transitions — blips shorter than this never "
                            "flip the mode")
    serve.add_argument("--serve_brownout_wait_ms", type=float, default=1.0,
                       help="degraded-mode batcher flush deadline, replacing "
                            "--max_batch_wait_ms while browned out")
    serve.add_argument("--serve_allow_chaos", action="store_true",
                       dest="serve_allow_chaos",
                       help="arm POST /chaos (accepts a vitax/faults.py "
                            "plan JSON body, installed live) for chaos "
                            "drills — never enable in production")
    serve.add_argument("--serve_cache_max", type=int, default=0,
                       help="fleet router prediction-cache entries "
                            "(0 = off); exact content-addressed hits "
                            "bypass dispatch entirely")
    serve.add_argument("--serve_cache_ttl_s", type=float, default=300.0,
                       help="prediction-cache entry lifetime in seconds")
    serve.add_argument("--serve_batch_window_ms", type=float, default=0.0,
                       help="fleet router cross-replica continuous "
                            "batching window (0 = off): concurrent "
                            "/predict bodies compose into one "
                            "/predict_batch per group")
    serve.add_argument("--serve_batch_max", type=int, default=0,
                       help="composed-group size cap "
                            "(0 = --serve_max_batch)")

    # scenario registry (vitax/programs/registry.py)
    scen = parser.add_argument_group("vitax scenarios (vitax/programs/)")
    scen.add_argument("--task", type=str, default="train",
                      choices=["train", "finetune", "probe", "distill"],
                      help="which registered scenario to run: "
                           "train = reference pretraining (CE over labels); "
                           "finetune = warm start from --init_npz with the "
                           "head re-initialized for a new --num_classes "
                           "(--reinit_head / shape mismatch) and optional "
                           "--backbone_lr_mult; "
                           "probe = linear probe — backbone frozen via "
                           "optax masking, optimizer moments exist for the "
                           "head only; "
                           "distill = knowledge distillation — frozen "
                           "teacher (--teacher_npz) and student in ONE "
                           "jitted program, loss (1-alpha)*CE + alpha*KL "
                           "at --distill_temp")
    scen.add_argument("--init_npz", type=str, default="",
                      help="finetune/probe warm start: consolidated npz "
                           "export (vitax.checkpoint.consolidate) loaded "
                           "into the fresh sharded state")
    scen.add_argument("--teacher_npz", type=str, default="",
                      help="distillation teacher: consolidated npz export "
                           "(quantized exports dequantize to f32 for the "
                           "teacher forward)")
    scen.add_argument("--reinit_head", action="store_true",
                      dest="reinit_head",
                      help="finetune: keep the fresh head init even when "
                           "the export's head shapes match")
    scen.add_argument("--backbone_lr_mult", type=float, default=1.0,
                      help="finetune: scale non-head updates by this after "
                           "AdamW (1.0 = off)")
    scen.add_argument("--distill_alpha", type=float, default=0.5,
                      help="distill loss mix: (1-alpha)*CE + alpha*KL")
    scen.add_argument("--distill_temp", type=float, default=2.0,
                      help="distill softmax temperature (KL scaled by T^2)")
    return parser


def config_fields_from_namespace(ns: argparse.Namespace) -> dict:
    """Config kwargs from a parsed namespace — tolerant of extra attributes,
    so tools may extend build_parser() with their own flags and still build a
    Config from the shared surface (tools/comm_audit.py does)."""
    return {f.name: getattr(ns, f.name) for f in dataclasses.fields(Config)}


def parse_config(argv: Optional[Tuple[str, ...]] = None) -> Config:
    """Two-phase parse so --preset_file (a committed autotune winner,
    presets/<model>_<topology>.json) becomes the DEFAULTS layer: the preset's
    resolved knobs are installed via parser.set_defaults() and the command
    line is re-parsed, so an explicit CLI flag still wins over the preset.
    batch_size stays at the trainer's own default/flag — the preset stores
    per-chip batch and the device count is unknown at parse time."""
    parser = build_parser()
    parser.add_argument("--preset_file", default="",
                        help="autotune preset JSON whose knobs become the "
                             "parser defaults (explicit flags win)")
    ns = parser.parse_args(argv)
    if ns.preset_file:
        from vitax.tune.preset import config_defaults_from_preset, load_preset
        parser.set_defaults(**config_defaults_from_preset(
            load_preset(ns.preset_file)))
        ns = parser.parse_args(argv)
    return Config(**config_fields_from_namespace(ns)).validate()
