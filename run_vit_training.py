#!/usr/bin/env python3
"""vitax training entry point — CLI-compatible with the reference's
run_vit_training.py (same 26 flags, same defaults; reference :327-364).

Launch (single host; each pod host runs the same command — see README):
    python3 run_vit_training.py --fake_data ...
"""

from vitax.platform import force_cpu_if_requested

force_cpu_if_requested()

from vitax.config import parse_config
from vitax.train.loop import train


def main(argv=None):
    cfg = parse_config(argv)
    train(cfg)
    # multi-process runs must also EXIT together: a rank that wins the
    # teardown race kills the coordination service under its peers and a
    # clean drain reads as dirty (see vitax/distributed.orderly_shutdown)
    from vitax.distributed import orderly_shutdown
    orderly_shutdown()


if __name__ == "__main__":
    main()
